"""Contract-lint subsystem (analysis/): engine semantics, and one
deliberately-violating fixture per rule proving each rule actually FIRES
— an extra psum over budget, a callback / oversized folded constant in a
loop body, an f64 leak in an f32 program, a dropped donation, and a
config field omitted from the cache key / snapshot fingerprint (the
PR-5/PR-6 review-hardening bug class, now a mechanical failure).

The current tree must be CLEAN: tier-1 runs the fast lint in-process;
the full pass (donation + fingerprint sweeps, ~30 s) is `slow`-marked
and exercised by `pcg-tpu lint` / hw_session step 0.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pcg_mpi_solver_tpu.analysis import engine
from pcg_mpi_solver_tpu.analysis.engine import Finding, apply_baseline
from pcg_mpi_solver_tpu.analysis.programs import DonationSurface, Program
from pcg_mpi_solver_tpu.analysis.rules_jaxpr import (
    check_collective_budget, check_dtype_discipline, check_hot_loop_purity)
from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS, make_mesh


# ---------------------------------------------------------------------------
# synthetic Program fixtures
# ---------------------------------------------------------------------------

def _toy_program(body_fn, budget, role="f64", dtype=jnp.float64,
                 n_trips=3, variant="classic", width=8):
    """A 2-part shard_map'd while-loop program, traced like the real
    canonical matrix entries."""
    mesh = make_mesh(2)
    P = jax.sharding.PartitionSpec(PARTS_AXIS)

    def prog(x):
        def cond(c):
            return c[0] < n_trips

        return jax.lax.while_loop(cond, body_fn, (0, x))[1]

    fn = jax.jit(jax.shard_map(prog, mesh=mesh, in_specs=(P,),
                               out_specs=P, check_vma=False))
    jx = jax.make_jaxpr(fn)(jnp.zeros((2, width), dtype))
    return Program(name="toy", backend="general", variant=variant,
                   nrhs=1, role=role, jaxpr=jx,
                   collective_budget=budget, n_iface=1)


def _body_psums(n):
    def body(c):
        i, v = c
        for _ in range(n):
            v = v + jax.lax.psum(v, PARTS_AXIS)
        return i + 1, v

    return body


# ---------------------------------------------------------------------------
# rule: collective-budget
# ---------------------------------------------------------------------------

def test_collective_budget_clean_within_budget():
    prog = _toy_program(_body_psums(2), {"psum": 2})
    assert check_collective_budget(prog) == []


def test_collective_budget_fires_on_extra_psum():
    """The seeded violation: one psum beyond the declared budget — the
    exact 'silently re-serialized reduction' regression."""
    prog = _toy_program(_body_psums(3), {"psum": 2})
    findings = check_collective_budget(prog)
    assert len(findings) == 1
    assert findings[0].rule == "collective-budget"
    assert "'psum': 3" in findings[0].message


def test_collective_budget_fires_on_undercounted_budget():
    """An UNDER-count fails too: the declaration (and the comm.* gauges
    reading the same table) would advertise collectives that no longer
    exist."""
    prog = _toy_program(_body_psums(1), {"psum": 2})
    assert check_collective_budget(prog) != []


def test_collective_budget_fires_on_undeclared_collective_kind():
    def body(c):
        i, v = c
        v = v + jax.lax.psum(v, PARTS_AXIS)
        v = jax.lax.ppermute(v, PARTS_AXIS, [(0, 1), (1, 0)])
        return i + 1, v

    prog = _toy_program(body, {"psum": 1})
    findings = check_collective_budget(prog)
    assert findings and "ppermute" in findings[0].message


def test_budget_table_matches_comm_estimate_gauges():
    """The gauges and the proof read ONE table (ops/matvec.py): body
    budget = advertised healthy-iteration psums + the deferred check."""
    from pcg_mpi_solver_tpu.ops.matvec import (
        Ops, PCG_DEFERRED_CHECK_PSUMS)

    from pcg_mpi_solver_tpu.config import PCG_VARIANTS

    ops = Ops(n_loc=8, n_iface=4)
    for variant in PCG_VARIANTS:
        gauge = ops.comm_estimate(variant=variant)["psums_per_iter"]
        budget = ops.body_collective_budget(variant)["psum"]
        assert budget == gauge + PCG_DEFERRED_CHECK_PSUMS
    # pipelined's contract: ONE scalar psum, same count as fused —
    # the win is overlap (psum-overlap rule), not fewer collectives
    assert ops.comm_estimate(variant="pipelined")["psums_per_iter"] == \
        ops.comm_estimate(variant="fused")["psums_per_iter"]
    with pytest.raises(KeyError):
        ops.body_collective_budget("frobnicate")  # unknown variant: loud
    with pytest.raises(KeyError):
        ops.comm_estimate(variant="frobnicate")


# ---------------------------------------------------------------------------
# rule: psum-overlap (ISSUE 11)
# ---------------------------------------------------------------------------

def _overlapped_body(c):
    """Pipelined-shaped toy: the scalar psum reads only carry state and
    nothing downstream of it feeds the 'stencil' psum — independent in
    both directions, exactly the GV overlap property.  The trailing
    'deferred check' psum consumes the stencil output, like the real
    body (so the stencil collective itself is NOT independent)."""
    i, v = c
    a = jax.lax.psum(jnp.sum(v), PARTS_AXIS)       # overlappable scalar
    b = jax.lax.psum(v, PARTS_AXIS)                # 'stencil' collective
    chk = jax.lax.psum(jnp.sum(b), PARTS_AXIS)     # check reads stencil
    return i + 1, v + b + a + chk


def _serialized_body(c):
    """The regression the rule exists to catch: the scalar reduction
    consumes the stencil collective's output (the fused variant's
    serialization, reintroduced into a body claiming overlap)."""
    i, v = c
    b = jax.lax.psum(v, PARTS_AXIS)
    a = jax.lax.psum(jnp.sum(b), PARTS_AXIS)
    return i + 1, v + b + a


def test_psum_overlap_clean_on_overlapped_pipelined_body():
    from pcg_mpi_solver_tpu.analysis.rules_jaxpr import check_psum_overlap

    prog = _toy_program(_overlapped_body, {"psum": 3},
                        variant="pipelined")
    assert check_psum_overlap(prog) == []


def test_psum_overlap_fires_on_serialized_pipelined_body():
    from pcg_mpi_solver_tpu.analysis.rules_jaxpr import check_psum_overlap

    prog = _toy_program(_serialized_body, {"psum": 2},
                        variant="pipelined")
    findings = check_psum_overlap(prog)
    assert len(findings) == 1 and findings[0].rule == "psum-overlap"
    assert "serialized" in findings[0].message


def test_psum_overlap_fires_on_feeding_direction_too():
    """Serialization in the OTHER direction (the classic shape: the
    reduction's output feeds the stencil collective's operand) must
    fail a pipelined body as well — overlap demands independence both
    ways."""
    from pcg_mpi_solver_tpu.analysis.rules_jaxpr import check_psum_overlap

    def body(c):
        i, v = c
        a = jax.lax.psum(jnp.sum(v), PARTS_AXIS)
        b = jax.lax.psum(v * a, PARTS_AXIS)        # stencil consumes a
        return i + 1, v + b

    prog = _toy_program(body, {"psum": 2}, variant="pipelined")
    assert check_psum_overlap(prog) != []


def test_psum_overlap_negative_control_guards_the_walker():
    """An 'independent' psum showing up in a classic/fused body means
    the dependency walker lost edges — the rule must fail loudly there
    instead of letting the pipelined proof go vacuous."""
    from pcg_mpi_solver_tpu.analysis.rules_jaxpr import check_psum_overlap

    clean = _toy_program(_serialized_body, {"psum": 2}, variant="fused")
    assert check_psum_overlap(clean) == []
    broken = _toy_program(_overlapped_body, {"psum": 3}, variant="fused")
    findings = check_psum_overlap(broken)
    assert findings and "walker" in findings[0].message


def test_psum_overlap_rejects_vector_payload_as_the_independent_psum():
    """The one independent psum must be the small stacked scalar
    reduction; a stencil-sized payload that merely lost its consumers
    is not the latency-hiding claim."""
    from pcg_mpi_solver_tpu.analysis.rules_jaxpr import check_psum_overlap

    def body(c):
        i, v = c
        a = jax.lax.psum(v, PARTS_AXIS)            # vector, no consumers
        b = jax.lax.psum(v * 2.0, PARTS_AXIS)
        cden = jax.lax.psum(jnp.sum(b), PARTS_AXIS)
        return i + 1, v + b + cden + jax.lax.stop_gradient(a) * 0.0

    prog = _toy_program(body, {"psum": 3}, variant="pipelined", width=64)
    findings = check_psum_overlap(prog)
    assert findings and "payload" in findings[0].message


def test_psum_overlap_conservative_on_nested_loops():
    """Collectives inside a nested while/scan are marked mutually
    dependent (loop feedback can wire anything to anything) — the safe
    over-approximation: a pipelined body whose only psums live in a
    nested loop proves NOTHING overlappable, rather than vacuously
    passing."""
    from pcg_mpi_solver_tpu.analysis.rules_jaxpr import check_psum_overlap

    def body(c):
        i, v = c

        def inner(j, acc):
            return acc + jax.lax.psum(acc, PARTS_AXIS) \
                + jax.lax.psum(jnp.sum(acc), PARTS_AXIS)

        return i + 1, jax.lax.fori_loop(0, 2, inner, v)

    prog = _toy_program(body, {"psum": 2}, variant="pipelined")
    assert check_psum_overlap(prog) != []


def test_psum_overlap_conservative_on_singleton_nested_loop_psum():
    """The degenerate nested-loop case: a body whose ONLY scalar psum
    sits inside a nested fori_loop.  Mutual marking between nested
    collectives is vacuous for a singleton, so the walker must mark it
    SELF-dependent (its prior trip feeds it through loop carry) — the
    rule fails rather than certifying a serialized-inside-a-loop psum
    as the overlappable reduction."""
    from pcg_mpi_solver_tpu.analysis.rules_jaxpr import check_psum_overlap

    def body(c):
        i, v = c

        def inner(j, acc):
            return acc + jax.lax.psum(jnp.sum(acc), PARTS_AXIS)

        return i + 1, jax.lax.fori_loop(0, 2, inner, v)

    prog = _toy_program(body, {"psum": 1}, variant="pipelined")
    assert check_psum_overlap(prog) != []


# ---------------------------------------------------------------------------
# rule: hot-loop-purity
# ---------------------------------------------------------------------------

def test_hot_loop_purity_clean():
    prog = _toy_program(_body_psums(1), {"psum": 1})
    assert check_hot_loop_purity(prog) == []


def test_hot_loop_purity_fires_on_callback_in_body():
    def body(c):
        i, v = c
        jax.debug.callback(lambda a: None, v.sum())
        return i + 1, v + 1.0

    prog = _toy_program(body, {})
    findings = check_hot_loop_purity(prog)
    assert len(findings) == 1
    assert "debug_callback" in findings[0].message


def test_hot_loop_purity_fires_on_oversized_folded_const():
    """A trace-time-captured operand array feeding the loop (the AOT
    export bloat class)."""
    big = np.arange(100_000, dtype=np.float64)

    def body(c):
        i, v = c
        return i + 1, v + jnp.asarray(big)[:8]

    prog = _toy_program(body, {})
    findings = check_hot_loop_purity(prog)
    assert len(findings) == 1
    assert "folded constant" in findings[0].message
    assert "100000" in findings[0].message


def test_hot_loop_purity_small_consts_pass():
    small = np.arange(8, dtype=np.float64)

    def body(c):
        i, v = c
        return i + 1, v + jnp.asarray(small)

    assert check_hot_loop_purity(_toy_program(body, {})) == []


# ---------------------------------------------------------------------------
# rule: dtype-discipline
# ---------------------------------------------------------------------------

def test_dtype_discipline_fires_on_f64_leak():
    def body(c):
        i, v = c
        y = v.astype(jnp.float64) * 2.0        # the leak
        return i + 1, y.astype(jnp.float32)

    prog = _toy_program(body, {}, role="f32", dtype=jnp.float32)
    findings = check_dtype_discipline(prog)
    assert len(findings) == 1
    assert "float64" in findings[0].message


def test_dtype_discipline_weak_scalars_and_f64_role_exempt():
    def body(c):
        i, v = c
        return i + 1, v * 2.0 + 1.5       # weak python-float literals

    assert check_dtype_discipline(
        _toy_program(body, {}, role="f32", dtype=jnp.float32)) == []
    # f64-role programs are out of scope by construction
    leaky = _toy_program(_body_psums(1), {}, role="f64")
    assert check_dtype_discipline(leaky) == []


# ---------------------------------------------------------------------------
# rule: donation-integrity
# ---------------------------------------------------------------------------

def test_donation_check_passes_on_real_aliasing():
    from pcg_mpi_solver_tpu.analysis.programs import check_donation

    def step(c, y):
        return {"a": c["a"] + y, "b": c["b"] * 2.0}

    c = {"a": jnp.zeros((4, 4)), "b": jnp.ones((4, 4))}
    fn = jax.jit(step, donate_argnums=(0,))
    assert check_donation(DonationSurface(
        "good", fn, (c, jnp.ones((4, 4))), c)) == []


def test_donation_check_fires_on_dropped_donation():
    """The seeded violation: the donated carry has no matching output,
    so jax SILENTLY drops the aliasing — the dispatch copies."""
    from pcg_mpi_solver_tpu.analysis.programs import check_donation

    def step(c, y):
        return y.sum()

    c = {"a": jnp.zeros((4, 4)), "b": jnp.ones((4, 4))}
    fn = jax.jit(step, donate_argnums=(0,))
    errs = check_donation(DonationSurface(
        "bad", fn, (c, jnp.ones((4, 4))), c))
    assert len(errs) == 1
    assert "dropped" in errs[0]


# ---------------------------------------------------------------------------
# rule: fingerprint-completeness
# ---------------------------------------------------------------------------

def test_fingerprint_rule_clean_on_real_surfaces():
    """tol is trace-affecting and covered by BOTH real surfaces."""
    from pcg_mpi_solver_tpu.analysis.rules_config import (
        check_fingerprint_completeness)

    assert check_fingerprint_completeness(fields=["tol"]) == []


def test_fingerprint_rule_catches_field_omitted_from_cache_key():
    """The acceptance fixture: a config field deliberately dropped from
    step_cache_key's payload turns into a mechanical finding."""
    from pcg_mpi_solver_tpu.analysis.rules_config import (
        check_fingerprint_completeness)
    from pcg_mpi_solver_tpu.cache.keys import step_cache_key

    def leaky_key(**kw):
        solver = dict(kw.get("solver") or {})
        solver.pop("tol", None)               # the deliberate omission
        kw["solver"] = solver
        return step_cache_key(**kw)

    findings = check_fingerprint_completeness(fields=["tol"],
                                              key_fn=leaky_key)
    assert len(findings) == 1
    assert "step_cache_key" in findings[0].message
    assert findings[0].loc == "field:SolverConfig.tol"


def test_fingerprint_rule_catches_field_omitted_from_snapshot_fp():
    from pcg_mpi_solver_tpu.analysis.rules_config import (
        check_fingerprint_completeness)

    findings = check_fingerprint_completeness(
        fields=["tol"], fp_fn=lambda solver: {"model": "const"})
    assert len(findings) == 1
    assert "_fingerprint" in findings[0].message


def test_structural_key_components_bite():
    from pcg_mpi_solver_tpu.analysis.rules_config import (
        check_structural_key_components)
    from pcg_mpi_solver_tpu.cache.keys import step_cache_key

    assert check_structural_key_components() == []

    def nrhs_blind(**kw):
        kw["nrhs"] = 1
        return step_cache_key(**kw)

    findings = check_structural_key_components(key_fn=nrhs_blind)
    assert len(findings) == 1
    assert "nrhs" in findings[0].message


def test_runconfig_fields_all_classified(monkeypatch):
    from pcg_mpi_solver_tpu.analysis import rules_config as rc

    assert rc.check_runconfig_classified() == []
    # an unclassified (e.g. freshly added) RunConfig field is a finding
    monkeypatch.setattr(
        rc, "TRACE_NEUTRAL_RUNCONFIG",
        rc.TRACE_NEUTRAL_RUNCONFIG - {"cache_dir"})
    findings = rc.check_runconfig_classified()
    assert len(findings) == 1
    assert "cache_dir" in findings[0].loc


def test_pre_existing_snapshots_resume_across_the_fp_extension(tmp_path):
    """Back-compat: snapshots written BEFORE the fingerprint gained the
    new numerics keys (dot_dtype, max_stag_steps, inner_tol,
    mixed_knobs, trace_len) must still load — the knobs existed but were
    unrecorded, so legacy entries skip the new checks instead of
    mismatching on upgrade (the PR-6 nrhs shim precedent)."""
    from pcg_mpi_solver_tpu.utils.checkpoint import SnapshotStore

    fp = {"model": "m", "nrhs": 1, "dot_dtype": "float64",
          "max_stag_steps": 3, "inner_tol": 1e-5,
          "mixed_knobs": [0, 0, 0.7, 30.0], "trace_len": 0}
    store = SnapshotStore(str(tmp_path), fp)
    store.save(1, {"x": np.zeros(4)})
    # doctor the stored fingerprint back to its pre-extension shape
    path = store._file(1)
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    saved = json.loads(bytes(flat["__fingerprint"]).decode())
    for k in ("dot_dtype", "max_stag_steps", "inner_tol", "mixed_knobs",
              "trace_len"):
        saved.pop(k)
    flat["__fingerprint"] = np.frombuffer(
        json.dumps(saved, sort_keys=True).encode(), dtype=np.uint8).copy()
    np.savez_compressed(path, **flat)
    state = store.load(1)          # legacy entry: must NOT mismatch
    assert state is not None and np.all(state["x"] == 0)
    # a snapshot that DID record the field still fails loudly on drift
    store.save(2, {"x": np.zeros(4)})
    store2 = SnapshotStore(str(tmp_path), dict(fp, max_stag_steps=9))
    with pytest.raises(ValueError, match="max_stag_steps"):
        store2.load(2)


def test_snapshot_fingerprint_carries_the_new_numerics_fields():
    """The gaps this PR's sweep found (dot_dtype, max_stag_steps,
    inner_tol, mixed knobs, trace ring length) are fingerprinted."""
    from pcg_mpi_solver_tpu.analysis.programs import build_solver
    from pcg_mpi_solver_tpu.utils.checkpoint import _fingerprint

    fp = _fingerprint(build_solver("general"))
    for key in ("dot_dtype", "max_stag_steps", "inner_tol",
                "mixed_knobs", "trace_len", "pcg_variant", "nrhs"):
        assert key in fp, key


# ---------------------------------------------------------------------------
# engine: registry, baseline, reports, end-to-end
# ---------------------------------------------------------------------------

def test_rule_catalog_complete():
    rules = {r.id: r for r in engine.list_rules()}
    expected = {"collective-budget", "hot-loop-purity", "dtype-discipline",
                "donation-integrity", "fingerprint-completeness",
                "recovery-paths", "recovery-coverage", "telemetry-schema",
                "cost-model-completeness", "partition-key-components",
                "scope-labels", "doc-schema-sync",
                "serve-admission-events"}
    assert expected <= set(rules)
    assert len(expected) >= 5
    # the pre-hardware-window gate covers the structural claims
    assert rules["collective-budget"].fast
    assert rules["recovery-paths"].fast
    assert rules["recovery-coverage"].fast
    assert rules["cost-model-completeness"].fast
    assert rules["partition-key-components"].fast
    assert rules["scope-labels"].fast
    assert rules["doc-schema-sync"].fast
    assert rules["serve-admission-events"].fast
    assert not rules["fingerprint-completeness"].fast


# ----------------------------------------------------------------------
# scope-labels (ISSUE 15): trace-attribution named scopes in every loop
# ----------------------------------------------------------------------

def test_scope_labels_clean_on_real_programs():
    """Every canonical program (all variants, scalar + blocked) carries
    all four pcg/* phase labels, and the parser-side loudness probe
    passes on the real bucketer."""
    from pcg_mpi_solver_tpu.analysis.programs import build_programs
    from pcg_mpi_solver_tpu.analysis.rules_jaxpr import (
        check_scope_labels, check_unknown_label_loudness)

    for prog in build_programs(fast=True):
        assert check_scope_labels(prog) == [], prog.name
    assert check_unknown_label_loudness() == []


def test_scope_labels_fires_on_missing_label():
    """A label the trace consumer buckets on but no program carries
    (here: a seeded extra phase) must fire per program — a hot loop
    that lost its named scope silently moves its time to 'other'."""
    from pcg_mpi_solver_tpu.analysis.programs import build_programs
    from pcg_mpi_solver_tpu.analysis.rules_jaxpr import (
        check_scope_labels)

    prog = build_programs(fast=True)[0]
    seeded = {"pcg/matvec": "matvec", "pcg/ghost_phase": "ghost"}
    findings = check_scope_labels(prog, phase_scopes=seeded)
    assert len(findings) == 1
    assert "pcg/ghost_phase" in findings[0].message
    assert findings[0].loc == f"program:{prog.name}"
    # ...and a toy program with no scopes at all fires on every label
    toy = _toy_program(_body_psums(1), {"psum": 1})
    all_missing = check_scope_labels(toy)
    assert len(all_missing) == 4


def test_scope_labels_unknown_label_loudness_probe_fires():
    """The probe must catch a bucketer that silently DROPS unbucketable
    time or unknown pcg/* labels (seeded broken implementations)."""
    from pcg_mpi_solver_tpu.analysis.rules_jaxpr import (
        check_unknown_label_loudness)

    def drops_unknowns(ops, scope_map):
        from pcg_mpi_solver_tpu.obs.perf import PHASES

        return {"phases": {ph: {"us": 0.0, "events": 0}
                           for ph in PHASES},
                "other_us": 0.0, "other_events": 0,
                "unknown_scopes": {}}

    findings = check_unknown_label_loudness(bucket_fn=drops_unknowns)
    assert len(findings) == 2       # dropped time AND dropped label
    assert any("DROPPED" in f.message for f in findings)
    assert any("unknown_scopes" in f.message for f in findings)

    def crashes(ops, scope_map):
        raise RuntimeError("boom")

    findings = check_unknown_label_loudness(bucket_fn=crashes)
    assert len(findings) == 1
    assert "crashed" in findings[0].message


# ----------------------------------------------------------------------
# recovery-coverage (ISSUE 9): dispatch surfaces wrapped or exempted
# ----------------------------------------------------------------------

def test_recovery_coverage_clean_on_real_tree():
    from pcg_mpi_solver_tpu.analysis.rules_ast import (
        recovery_coverage_rule)

    assert recovery_coverage_rule(None) == []


def test_recovery_coverage_seeded_violations():
    """Every failure class fires on seeded sources: an unregistered
    Krylov dispatch surface, a registered surface that dropped its
    harness call, an exempt surface without the documented marker, and
    a stale registry entry."""
    from pcg_mpi_solver_tpu.analysis.rules_ast import (
        check_recovery_coverage)

    rel = "pcg_mpi_solver_tpu/solver/driver.py"

    # (1) unregistered surface: a new method opening a terminal span
    src = (
        "class Solver:\n"
        "    def step(self):\n"
        "        # recovery-exempt: test stub\n"
        "        self._step_fn()\n"
        "    def _step_chunked(self):\n"
        "        run_with_recovery()\n"
        "    def _solve_many_chunked(self):\n"
        "        run_many_with_recovery()\n"
        "    def solve_many(self):\n"
        "        return self._dispatch_with_retry('solve_many', f)\n"
        "    def sneaky_new_path(self):\n"
        "        with self._rec.dispatch('many_cycle'):\n"
        "            pass\n")
    errs = check_recovery_coverage({rel: src})
    assert any("sneaky_new_path" in e and "not registered" in e
               for e in errs), errs
    assert not any("_step_chunked" in e for e in errs)

    # (2) registered surface that no longer calls its harness
    src2 = src.replace("run_with_recovery()", "pass")
    errs2 = check_recovery_coverage({rel: src2})
    assert any("_step_chunked" in e and "run_with_recovery" in e
               for e in errs2), errs2

    # (3) exempt surface without the documented marker
    src3 = src.replace("        # recovery-exempt: test stub\n", "")
    errs3 = check_recovery_coverage({rel: src3})
    assert any("`step`" in e and "recovery-exempt" in e
               for e in errs3), errs3

    # (4) stale registry entry: the registered function vanished
    errs4 = check_recovery_coverage({rel: "x = 1\n"})
    assert any("no such function" in e for e in errs4), errs4


# ----------------------------------------------------------------------
# consensus-coverage (ISSUE 18): host-side collectives on the dispatch
# path route their verdicts through parallel/consensus or are exempted
# ----------------------------------------------------------------------

def test_consensus_coverage_clean_on_real_tree():
    from pcg_mpi_solver_tpu.analysis.rules_ast import (
        consensus_coverage_rule)

    assert consensus_coverage_rule(None) == []


def test_consensus_coverage_seeded_violations():
    """Every failure class fires on seeded sources: an unregistered
    collective call site, a registered site that dropped its consensus
    call, an exempt site without the documented marker, and a stale
    registry entry — plus the `warmup` negative control (the unrelated
    compile-warmup method must never register as a collective)."""
    from pcg_mpi_solver_tpu.analysis.rules_ast import (
        check_consensus_coverage)

    rel = "pcg_mpi_solver_tpu/solver/driver.py"
    src = (
        "def _pallas_enabled():\n"
        "    # consensus-exempt: test stub\n"
        "    return process_allgather(x)\n"
        "class Solver:\n"
        "    def __init__(self):\n"
        "        ok = agree_flag(comm, ok)\n"
        "    def _exchange_export_glue(self):\n"
        "        # consensus-exempt: test stub\n"
        "        mh.process_allgather(i)\n"
        "    def solve(self):\n"
        "        # consensus-exempt: test stub\n"
        "        multihost_utils.sync_global_devices('prepared')\n"
        "    def warm_compile(self):\n"
        "        self.engine.warmup()\n"
        "    def sneaky_branch(self):\n"
        "        if comm.allreduce(v, 'min'):\n"
        "            pass\n")

    # (0) clean seeded tree modulo the one unregistered site; warmup
    # must not be flagged
    errs = check_consensus_coverage({rel: src})
    assert any("sneaky_branch" in e and "not registered" in e
               for e in errs), errs
    assert not any("warm_compile" in e for e in errs), errs
    assert not any("__init__" in e for e in errs), errs

    # (2) registered site that no longer calls its consensus primitive
    src2 = src.replace("ok = agree_flag(comm, ok)", "pass")
    errs2 = check_consensus_coverage({rel: src2})
    assert any("__init__" in e and "agree_flag" in e
               for e in errs2), errs2

    # (3) exempt site without the documented marker
    src3 = src.replace(
        "        # consensus-exempt: test stub\n"
        "        multihost_utils.sync_global_devices('prepared')\n",
        "        multihost_utils.sync_global_devices('prepared')\n")
    errs3 = check_consensus_coverage({rel: src3})
    assert any("`solve`" in e and "consensus-exempt" in e
               for e in errs3), errs3

    # (4) stale registry entry: the registered function vanished
    errs4 = check_consensus_coverage({rel: "x = 1\n"})
    assert any("no such function" in e for e in errs4), errs4


# ----------------------------------------------------------------------
# serve-admission-events (ISSUE 19): every admission-decision outcome
# emits its schema-versioned telemetry event
# ----------------------------------------------------------------------

def test_serve_admission_events_clean_on_real_tree():
    from pcg_mpi_solver_tpu.analysis.rules_ast import (
        serve_admission_events_rule)

    assert serve_admission_events_rule(None) == []


def test_serve_admission_events_seeded_violations():
    """Every failure class fires on seeded sources: a decision site
    that dropped its event emission (the silent-outcome regression the
    rule exists for), a stale registry entry, and a registry kind the
    telemetry schema no longer knows."""
    from pcg_mpi_solver_tpu.analysis.rules_ast import (
        ADMISSION_EVENT_SITES, check_admission_events)

    rel = "pcg_mpi_solver_tpu/serve/admission.py"
    src = (
        "class AdmissionController:\n"
        "    def admit(self, spec, now=None):\n"
        "        self._rec.event('job_admit', job=spec['job'])\n"
        "    def _reject(self, job, reason, **fields):\n"
        "        self._rec.event('job_reject', job=job, reason=reason)\n"
        "    def shed_past_deadline(self, now=None):\n"
        "        self._rec.event('job_shed', job='x', reason='r')\n")
    assert check_admission_events({rel: src}) == []

    # (1) a decision site stops emitting: the outcome goes silent
    src1 = src.replace(
        "        self._rec.event('job_shed', job='x', reason='r')\n",
        "        pass\n")
    errs1 = check_admission_events({rel: src1})
    assert any("shed_past_deadline" in e and "`job_shed`" in e
               for e in errs1), errs1

    # (2) stale registry entry: the registered function vanished
    src2 = src.replace("def admit", "def admit_renamed")
    errs2 = check_admission_events({rel: src2})
    assert any("`admit`" in e and "no such function" in e
               for e in errs2), errs2

    # (3) registry kinds must exist in obs/schema EVENT_KINDS — the
    # real registry is checked live against the real schema
    from pcg_mpi_solver_tpu.obs.schema import EVENT_KINDS
    for kinds in ADMISSION_EVENT_SITES.values():
        for kind in kinds:
            assert kind in EVENT_KINDS, kind

    # an emit of the WRONG kind does not satisfy the requirement
    src3 = src.replace("'job_admit'", "'job_done'")
    errs3 = check_admission_events({rel: src3})
    assert any("`admit`" in e and "`job_admit`" in e for e in errs3), errs3


# ----------------------------------------------------------------------
# cost-model-completeness (ISSUE 12): the analytic per-iteration cost
# model covers every canonical variant x precond combination, loudly
# ----------------------------------------------------------------------

def test_cost_model_completeness_clean_on_real_tree():
    from pcg_mpi_solver_tpu.analysis.rules_config import (
        cost_model_completeness_rule)

    assert cost_model_completeness_rule(None) == []


def test_cost_model_completeness_seeded_violations():
    """Every failure class fires on seeded model functions: a combo the
    model cannot produce, a degenerate (zero/partial-phase) entry, an
    unknown name silently accepted, and the wrong exception type for
    an unknown name."""
    from pcg_mpi_solver_tpu.analysis.rules_config import (
        check_cost_model_completeness)
    from pcg_mpi_solver_tpu.obs import perf as _perf

    shape = _perf.ProblemShape(n_dof=10_000, n_parts=4, n_iface=500,
                               elem_groups=((24, 3_000),))

    def real(v, p, r):
        return _perf.cost_model(shape, v, p, r)

    # (0) the real model over the real tables: no findings
    assert check_cost_model_completeness(model_fn=real) == []

    # (1) a canonical combo the model has no entry for (the new-variant-
    # landed-in-one-table-only failure): loud finding naming the combo
    def missing_combo(v, p, r):
        if (v, p) == ("pipelined", "mg"):
            raise KeyError(p)
        return real(v, p, r)

    errs = check_cost_model_completeness(model_fn=missing_combo)
    assert any("pipelined" in f.loc and "mg" in f.loc and
               "no entry" in f.message for f in errs), errs

    # (2) a degenerate entry: a dropped phase or a zero prediction must
    # read as a finding, not as "this phase is free"
    def dropped_phase(v, p, r):
        cm = dict(real(v, p, r))
        cm["phases"] = {k: val for k, val in cm["phases"].items()
                        if k != "axpy"}
        return cm

    errs2 = check_cost_model_completeness(model_fn=dropped_phase)
    assert any("degenerate" in f.message and "axpy" in f.message
               for f in errs2), errs2

    def zero_pred(v, p, r):
        return {**real(v, p, r), "predicted_ms_per_iter": 0.0}

    errs3 = check_cost_model_completeness(model_fn=zero_pred)
    assert any("degenerate" in f.message for f in errs3), errs3

    # (3) unknown names silently accepted: the fabricated-prediction
    # failure the loudness probes exist for
    def silent_default(v, p, r):
        try:
            return real(v, p, r)
        except KeyError:
            return real("classic", "jacobi", r)

    errs4 = check_cost_model_completeness(model_fn=silent_default)
    assert any(f.loc == "probe:unknown-variant" and "silently" in
               f.message for f in errs4), errs4
    assert any(f.loc == "probe:unknown-precond" for f in errs4), errs4

    # (4) the wrong exception type: consumers catch KeyError as the
    # table-out-of-sync signal, anything else is an internal failure
    def wrong_exc(v, p, r):
        try:
            return real(v, p, r)
        except KeyError:
            raise ValueError(f"{v}/{p}")

    errs5 = check_cost_model_completeness(model_fn=wrong_exc)
    assert any("instead of KeyError" in f.message for f in errs5), errs5


def test_baseline_suppression_and_undocumented_entry():
    f1 = Finding(rule="r", loc="a", message="m")
    f2 = Finding(rule="r", loc="b", message="m")
    active, suppressed = apply_baseline(
        [f1, f2], [{"rule": "r", "loc": "a", "reason": "known"}])
    assert active == [f2] and suppressed == [f1]
    # an entry without a reason becomes a finding itself
    active, _ = apply_baseline([], [{"rule": "r", "loc": "x"}])
    assert len(active) == 1 and active[0].rule == "baseline"
    # a documented entry matching NO current finding is a stale-
    # suppression WARNING (reported, but does not fail the lint)
    active, _ = apply_baseline(
        [], [{"rule": "r", "loc": "gone", "reason": "fixed long ago"}])
    assert len(active) == 1 and active[0].severity == "warn"
    assert "stale" in active[0].message


def test_shipped_baseline_is_empty():
    entries = engine.load_baseline(engine.DEFAULT_BASELINE)
    assert entries == []


def test_unknown_rule_id_raises():
    with pytest.raises(ValueError, match="unknown rule"):
        engine.run_lint(rule_ids=["no-such-rule"])


def test_fast_lint_clean_on_current_tree():
    """Tier-1 gate: the fast rules (source + artifact lints and the
    collective/purity/dtype proofs on the reduced matrix) hold on the
    tree as committed."""
    report = engine.run_lint(fast=True)
    assert report.errors == []
    assert report.findings == [], "\n".join(map(str, report.findings))
    assert report.clean and report.exit_code == 0
    assert "collective-budget" in report.rules_run


@pytest.mark.slow
def test_full_lint_clean_on_current_tree():
    report = engine.run_lint(fast=False)
    assert report.errors == []
    assert report.findings == [], "\n".join(map(str, report.findings))


def test_report_json_schema_roundtrip(tmp_path):
    report = engine.run_lint(rule_ids=["telemetry-schema"])
    doc = report.to_dict()
    assert doc["schema"] == "pcg-tpu-lint-report/1"
    json.loads(json.dumps(doc))   # json-serializable end to end


def test_cli_exit_codes(tmp_path):
    """`python -m pcg_mpi_solver_tpu.analysis` (jax-light rule subset
    to keep the subprocess cheap): 0 on clean, 2 on unknown rule."""
    out = tmp_path / "report.json"
    ok = subprocess.run(
        [sys.executable, "-m", "pcg_mpi_solver_tpu.analysis",
         "--rules", "telemetry-schema,recovery-paths",
         "--json", str(out)],
        capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    doc = json.loads(out.read_text())
    assert doc["clean"] is True
    bad = subprocess.run(
        [sys.executable, "-m", "pcg_mpi_solver_tpu.analysis",
         "--rules", "no-such-rule"],
        capture_output=True, text=True)
    assert bad.returncode == 2


def test_analysis_package_import_is_jax_free():
    """The import contract the package __init__ documents (same as the
    repo root package): importing analysis/ must not pull in jax."""
    code = ("import sys; sys.modules.pop('jax', None); "
            "assert 'jax' not in sys.modules; "
            "import pcg_mpi_solver_tpu.analysis; "
            "import pcg_mpi_solver_tpu.analysis.engine; "
            "import pcg_mpi_solver_tpu.analysis.rules_ast; "
            "import pcg_mpi_solver_tpu.analysis.rules_artifacts; "
            "import pcg_mpi_solver_tpu.analysis.rules_config; "
            "import pcg_mpi_solver_tpu.analysis.rules_jaxpr; "
            "assert 'jax' not in sys.modules, 'analysis imported jax'")
    import os

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)   # keep the package pin from importing jax
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, cwd="/root/repo")
    assert r.returncode == 0, r.stdout + r.stderr
