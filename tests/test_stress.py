"""Stress/strain export chain: principal values, strain fields, nodal
averaging — on both backends, validated against closed-form states."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.element import elasticity_matrix
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.driver import Solver
from pcg_mpi_solver_tpu.utils.io import RunStore


def test_principal_values_vs_eigvalsh():
    import jax.numpy as jnp
    from pcg_mpi_solver_tpu.ops.stress import principal_values

    rng = np.random.default_rng(0)
    n = 64
    voigt = rng.normal(size=(6, n))
    got = np.asarray(principal_values(jnp.asarray(voigt), axis=0))
    for i in range(n):
        xx, yy, zz, yz, xz, xy = voigt[:, i]
        T = np.array([[xx, xy, xz], [xy, yy, yz], [xz, yz, zz]])
        ref = np.sort(np.linalg.eigvalsh(T))[::-1]
        np.testing.assert_allclose(got[:, i], ref, rtol=1e-8, atol=1e-10)


def test_principal_values_degenerate_tensors():
    """Zero and isotropic tensors (all eigenvalues equal) must not NaN —
    the always-exported initial frame has exactly-zero strain."""
    import jax.numpy as jnp
    from pcg_mpi_solver_tpu.ops.stress import principal_values

    z = np.zeros((6, 4))
    z[:3, 1] = 2.5          # isotropic
    z[:3, 2] = -1.0
    z[0, 3] = 1e-30         # near-underflow
    got = np.asarray(principal_values(jnp.asarray(z), axis=0))
    assert np.all(np.isfinite(got))
    np.testing.assert_allclose(got[:, 0], 0.0, atol=1e-12)
    np.testing.assert_allclose(got[:, 1], 2.5, rtol=1e-10)
    np.testing.assert_allclose(got[:, 2], -1.0, rtol=1e-10)


def test_void_elements_backends_agree(tmp_path):
    """A ck=0 (void) element must yield identical nodal fields on both
    backends (counts include every real element, reference-faithful)."""
    model = make_cube_model(8, 4, 4, E=3.0, nu=0.2, load="traction")
    model.ck[5] = 0.0
    s1, store1 = _run_with_exports(model, 4, tmp_path / "a", backend="structured")
    s2, store2 = _run_with_exports(model, 4, tmp_path / "b", backend="general")
    for var in ("ES", "PS1"):
        f1 = _global_field(model, store1, var)
        f2 = _global_field(model, store2, var)
        assert np.all(np.isfinite(f1))
        np.testing.assert_allclose(f1, f2, rtol=1e-6,
                                   atol=1e-9 * np.abs(f2).max())


def _run_with_exports(model, n_parts, tmp_path, backend="auto", mesh_n=None):
    cfg = RunConfig(
        scratch_path=str(tmp_path), run_id="1",
        solver=SolverConfig(tol=1e-10, max_iter=3000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                       export_vars="U D ES PS PE"),
    )
    s = Solver(model, cfg, mesh=make_mesh(mesh_n or n_parts), n_parts=n_parts,
               backend=backend)
    store = RunStore(cfg.result_path, cfg.model_name)
    s.solve(store=store)
    return s, store


def _global_field(model, store, var, k=1):
    node_map = store.read_map("NodeId")
    a = np.zeros(model.n_node)
    a[node_map] = store.read_frame(var, k)
    return a


@pytest.mark.parametrize("backend,n_parts", [("general", 4), ("structured", 4)])
def test_patch_test_uniform_strain_fields(tmp_path, backend, n_parts):
    """Patch test: affine displacement u_x = eps*x prescribed on the whole
    boundary -> the interior solution and ALL nodal stress/strain fields must
    be the exact uniform confined-stretch state."""
    E, nu = 7.0, 0.25
    eps_xx = 0.1
    model = make_cube_model(8, 4, 4, h=0.25, E=E, nu=nu, load="traction",
                            load_value=0.0)
    # prescribe the affine field on all boundary nodes
    c = model.node_coords
    on_bnd = ((c[:, 0] == c[:, 0].min()) | (c[:, 0] == c[:, 0].max())
              | (c[:, 1] == c[:, 1].min()) | (c[:, 1] == c[:, 1].max())
              | (c[:, 2] == c[:, 2].min()) | (c[:, 2] == c[:, 2].max()))
    bnd_nodes = np.where(on_bnd)[0]
    model.fixed_dof = np.unique(
        (3 * bnd_nodes[:, None] + np.arange(3)).ravel())
    model.dof_eff = np.setdiff1d(np.arange(model.n_dof), model.fixed_dof,
                                 assume_unique=True)
    model.Ud[:] = 0.0
    model.Ud[0::3] = eps_xx * c[:, 0]
    model.F[:] = 0.0

    s, store = _run_with_exports(model, n_parts, tmp_path, backend=backend)
    assert s.backend == backend

    D = elasticity_matrix(E, nu)
    sig = D @ np.array([eps_xx, 0, 0, 0, 0, 0])

    # node maps cover every node exactly once
    node_map = store.read_map("NodeId")
    assert sorted(node_map) == list(range(model.n_node))

    ps1 = _global_field(model, store, "PS1")
    np.testing.assert_allclose(ps1, sig[0], rtol=1e-6)
    pe1 = _global_field(model, store, "PE1")
    np.testing.assert_allclose(pe1, eps_xx, rtol=1e-6)
    # uniform-stretch confined: PE2 = PE3 = 0 (up to solver tolerance)
    np.testing.assert_allclose(_global_field(model, store, "PE2"), 0, atol=1e-7)
    d = _global_field(model, store, "D")
    np.testing.assert_allclose(d, 0, atol=1e-12)
    es = _global_field(model, store, "ES")
    np.testing.assert_allclose(es, 2.0 / 3.0 * eps_xx, rtol=1e-6)


def test_backends_agree_on_nodal_fields(tmp_path):
    model = make_cube_model(8, 4, 4, E=3.0, nu=0.2, load="traction",
                            heterogeneous=True)
    s1, store1 = _run_with_exports(model, 4, tmp_path / "a", backend="structured")
    s2, store2 = _run_with_exports(model, 4, tmp_path / "b", backend="general")
    for var in ("PS1", "PS2", "PS3", "PE1", "ES"):
        f1 = _global_field(model, store1, var)
        f2 = _global_field(model, store2, var)
        np.testing.assert_allclose(f1, f2, rtol=1e-6,
                                   atol=1e-9 * np.abs(f2).max())