"""Distributed fault tolerance (ISSUE 18): group-consistent snapshot
epochs (two-phase commit markers), collective deadline guards with
dead-peer attribution off the flight shards, group-agreed consensus
verdicts, the ``@rank:`` fault domain, and elastic resume.

Unit layer: everything above exercised in-process with stub comms and
hand-built flight shards.  E2e layer: REAL two-process jax.distributed
runs — a rank killed mid-Krylov must surface as a named DeadPeerError
on the survivor within the deadline, a same-count relaunch must resume
bit-identically (scalar and blocked paths), and a committed 2-process
epoch must resume on ONE process (elastic) and finish."""

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.parallel.consensus import (
    agree, agree_flag, agree_trigger, agree_triggers, decode_trigger,
    encode_trigger)
from pcg_mpi_solver_tpu.resilience import (
    DeadPeerError, FaultPlan, GroupSnapshotStore, GuardedComm,
    InjectedDispatchError, SimulatedKill, collective_deadline_s,
    is_device_loss, suspect_dead_rank)

from test_distributed import _run_multiproc, make_mh_test_model


class _Cap:
    """Metrics sink collecting events for assertions."""

    def __init__(self):
        self.events = []

    def emit(self, ev):
        self.events.append(ev)

    def close(self):
        pass


def _kinds(cap, kind):
    return [e for e in cap.events if e["kind"] == kind]


# ----------------------------------------------------------------------
# Deadline knob + dead-peer attribution
# ----------------------------------------------------------------------

def test_collective_deadline_env(monkeypatch):
    monkeypatch.delenv("PCG_TPU_COLLECTIVE_DEADLINE_S", raising=False)
    assert collective_deadline_s() is None
    monkeypatch.setenv("PCG_TPU_COLLECTIVE_DEADLINE_S", "7.5")
    assert collective_deadline_s() == 7.5
    monkeypatch.setenv("PCG_TPU_COLLECTIVE_DEADLINE_S", "0")
    assert collective_deadline_s() is None
    monkeypatch.setenv("PCG_TPU_COLLECTIVE_DEADLINE_S", "soon")
    with pytest.warns(UserWarning, match="not a number"):
        assert collective_deadline_s() is None


def _write_shard(path, t, done=False):
    lines = [{"schema": 1, "t": t, "kind": "meta"}]
    if done:
        lines.append({"schema": 1, "t": t, "kind": "run_summary"})
    path.write_text("".join(json.dumps(ev) + "\n" for ev in lines))


def test_suspect_dead_rank_reads_peer_shard_tails(tmp_path):
    base = tmp_path / "fl.jsonl"
    now = time.time()
    _write_shard(tmp_path / "fl.p0.jsonl", now)          # self: excluded
    _write_shard(tmp_path / "fl.p1.jsonl", now - 45.0)   # silent 45s
    _write_shard(tmp_path / "fl.p2.jsonl", now - 5.0)
    rank, silent = suspect_dead_rank(str(base), self_index=0)
    assert rank == 1 and silent > 30.0
    # a peer that finished cleanly (run_summary) is not a suspect
    _write_shard(tmp_path / "fl.p1.jsonl", now - 45.0, done=True)
    rank, _ = suspect_dead_rank(str(base), self_index=0)
    assert rank == 2
    # nothing readable -> no verdict, never a raise
    assert suspect_dead_rank(str(tmp_path / "absent.jsonl"), 0) == (None,
                                                                    None)


class _HangComm:
    """HostComm stub whose collectives never come back (dead peer)."""

    n_procs = 2

    def allreduce(self, arr, op):
        time.sleep(300)


class _BoomComm:
    n_procs = 2

    def allreduce(self, arr, op):
        raise ValueError("boom")


def test_guardedcomm_deadline_names_suspect(tmp_path):
    base = tmp_path / "fl.jsonl"
    now = time.time()
    _write_shard(tmp_path / "fl.p0.jsonl", now)
    _write_shard(tmp_path / "fl.p1.jsonl", now - 45.0)
    cap = _Cap()
    rec = MetricsRecorder(sinks=[cap])
    g = GuardedComm(_HangComm(), deadline_s=0.3, recorder=rec,
                    flight_base=str(base), index=0)
    t0 = time.monotonic()
    with pytest.raises(DeadPeerError) as ei:
        g.barrier("chunk_boundary")
    assert time.monotonic() - t0 < 5.0          # bounded, not a hang
    msg = str(ei.value)
    assert "suspected dead peer: process 1" in msg
    assert "chunk_boundary" in msg
    # deliberately NOT device-loss shaped: the dispatch guard must
    # propagate a dead peer instead of burning retries on it
    assert not is_device_loss(ei.value)
    (ev,) = _kinds(cap, "collective_timeout")
    assert ev["label"] == "chunk_boundary" and ev["suspect"] == 1
    assert rec.counters["resilience.collective_timeout"] == 1


class _ResetComm:
    """A killed peer as gloo actually surfaces it: a FAST connection
    error out of the collective, not a hang."""

    n_procs = 2

    def allreduce(self, arr, op):
        raise RuntimeError("Gloo AllGather failed: [transport/tcp/pair.cc]"
                           " Read error: Connection reset by peer")


def test_guardedcomm_transport_failure_is_dead_peer(tmp_path):
    base = tmp_path / "fl.jsonl"
    _write_shard(tmp_path / "fl.p0.jsonl", time.time())
    _write_shard(tmp_path / "fl.p1.jsonl", time.time() - 1.0)
    cap = _Cap()
    g = GuardedComm(_ResetComm(), deadline_s=5.0,
                    recorder=MetricsRecorder(sinks=[cap]),
                    flight_base=str(base), index=0)
    with pytest.raises(DeadPeerError) as ei:
        g.barrier("chunk_boundary")
    assert "suspected dead peer: process 1" in str(ei.value)
    assert not is_device_loss(ei.value)          # must NOT burn retries
    assert isinstance(ei.value.__cause__, RuntimeError)
    (ev,) = _kinds(cap, "collective_timeout")
    assert ev["suspect"] == 1


def test_guardedcomm_transport_classified_without_deadline():
    """Regression: the transport-failure-to-DeadPeerError classification
    is a correctness concern, not a watchdog concern — a killed gloo
    peer's connection-reset error must get the dead-peer verdict even
    on a default-config run (PCG_TPU_COLLECTIVE_DEADLINE_S unset),
    instead of keeping its retryable-device-loss shape and burning
    dispatch-guard retries re-entering the dead group."""
    cap = _Cap()
    g = GuardedComm(_ResetComm(), deadline_s=None,
                    recorder=MetricsRecorder(sinks=[cap]), index=0)
    with pytest.raises(DeadPeerError) as ei:
        g.allreduce(np.ones(1), "min")
    assert not is_device_loss(ei.value)
    assert isinstance(ei.value.__cause__, RuntimeError)
    (ev,) = _kinds(cap, "collective_timeout")
    assert ev["deadline_s"] == 0.0          # verdict without a watchdog


def test_guardedcomm_passthrough_and_error_rethrow():
    # non-transport errors keep their own type, deadline armed or not
    g = GuardedComm(_BoomComm(), deadline_s=None, index=0)
    with pytest.raises(ValueError, match="boom"):
        g.allreduce(np.ones(1), "min")
    g = GuardedComm(_BoomComm(), deadline_s=5.0, index=0)
    with pytest.raises(ValueError, match="boom"):
        g.allreduce(np.ones(1), "min")


# ----------------------------------------------------------------------
# Consensus verdicts
# ----------------------------------------------------------------------

class _ScriptedComm:
    """HostComm-shaped stub: each allreduce pops the next scripted
    group result (None = lockstep-identical peers, pass through)."""

    def __init__(self, script=(), n_procs=2):
        self.n_procs = n_procs
        self.script = list(script)

    def allreduce(self, arr, op):
        out = np.asarray(arr, dtype=np.int64).copy()
        if self.script:
            nxt = self.script.pop(0)
            if nxt is not None:
                out[...] = np.asarray(nxt, dtype=np.int64)
        return out

    def allreduce_groups(self, groups):
        return [tuple(self.allreduce(a, op) for a in arrs)
                for arrs, op in groups]


def test_consensus_identity_without_group():
    assert agree(None, [3, 7], "min").tolist() == [3, 7]
    assert agree(_ScriptedComm(n_procs=1), [3], "max")[0] == 3
    assert agree_flag(None, True) is True
    assert agree_flag(None, 0) is False
    assert agree_trigger(None, "nan_carry") == "nan_carry"
    assert agree_trigger(None, None) is None
    assert agree_triggers(None, {1: "flag2"}, 4) == {1: "flag2"}


def test_trigger_codes_roundtrip():
    for t in (None, "device_loss", "nan_carry", "flag2", "flag4"):
        assert decode_trigger(encode_trigger(t)) == t
    with pytest.raises(ValueError):
        encode_trigger("meteor_strike")
    with pytest.raises(ValueError):
        decode_trigger(7)


def test_consensus_group_reduction():
    # a peer's alarm (device_loss=1) wins the max over this rank's None
    comm = _ScriptedComm(script=[encode_trigger("device_loss")])
    assert agree_trigger(comm, None) == "device_loss"
    # all-ranks-able: one peer's 0 vetoes the min
    assert agree_flag(_ScriptedComm(script=[0]), True) is False
    # packed per-column verdicts: only agreed columns come back
    comm = _ScriptedComm(script=[[0, encode_trigger("nan_carry"), 0,
                                  encode_trigger("flag4")]])
    assert agree_triggers(comm, {}, 4) == {1: "nan_carry", 3: "flag4"}


def test_collective_comm_real_group_without_deadline(monkeypatch):
    """Regression (review): Solver._collective_comm must return a REAL
    group on every multi-process run — the consensus agreements
    (snapshot commit markers, recovery ladder, resume epoch) are
    correctness-critical regardless of configuration — with the
    deadline watchdog layered on only when
    PCG_TPU_COLLECTIVE_DEADLINE_S is armed.  Before the fix it returned
    None without the knob, silently degrading every agree() to a local
    verdict (rank 0 committed epochs after checking only its OWN shard
    write)."""
    import pcg_mpi_solver_tpu.solver.driver as driver_mod
    from pcg_mpi_solver_tpu.solver.driver import Solver

    monkeypatch.delenv("PCG_TPU_COLLECTIVE_DEADLINE_S", raising=False)
    monkeypatch.setattr(driver_mod.jax, "process_count", lambda: 2)
    monkeypatch.setattr(driver_mod.jax, "process_index", lambda: 0)
    s = Solver.__new__(Solver)
    s._group_comm, s._setup_comm, s._rec = None, _ScriptedComm(), None
    comm = s._collective_comm()
    assert isinstance(comm, GuardedComm)
    assert comm.deadline_s is None and comm.n_procs == 2
    # consensus rounds genuinely reduce through the wrapped group
    assert agree(comm, [3], "max")[0] == 3
    # ... and the watchdog arms once the knob is set
    monkeypatch.setenv("PCG_TPU_COLLECTIVE_DEADLINE_S", "7")
    s._group_comm = None
    assert s._collective_comm().deadline_s == 7.0


# ----------------------------------------------------------------------
# @rank: fault domain
# ----------------------------------------------------------------------

def test_rank_fault_parse_and_single_process_semantics():
    p = FaultPlan("kill@rank:0:1, exc@rank:0")
    assert p.armed
    # exc@rank:0 == exc@rank:0:0 -> fires on dispatch 0 of THIS process
    with pytest.raises(InjectedDispatchError):
        p.on_dispatch()
    # kill@rank:0:1 -> boundary 1 of this process
    p.at_boundary({"x": np.ones(2)})
    with pytest.raises(SimulatedKill):
        p.at_boundary({"x": np.ones(2)})
    assert [f["point"] for f in p.fired] == ["rank-dispatch",
                                             "rank-boundary"]


def test_rank_fault_cannot_land_past_process_count():
    # single-process run: rank 1 does not exist -> the fault neither
    # fires nor is consumed/recorded (cannot-land contract)
    p = FaultPlan("kill@rank:1:0, nan@rank:1:0")
    carry = {"r": np.ones(3), "x": np.ones(3)}
    out = p.at_boundary(carry)
    assert np.all(np.isfinite(out["r"]))
    assert p.fired == []
    assert p._rank_faults["kill"] == {(1, 0): 1}     # still pending


def test_rank_fault_bad_specs_rejected():
    with pytest.raises(ValueError):
        FaultPlan("kill@rank:-1:2")
    with pytest.raises(ValueError):
        FaultPlan("kill@rank:")


# ----------------------------------------------------------------------
# Group-consistent snapshot epochs (two-phase commit)
# ----------------------------------------------------------------------

_FP2 = {"n_procs": 2, "tol": 1e-8}


def _pair_stores(path, fingerprint=None, recorder=None):
    fp = dict(_FP2 if fingerprint is None else fingerprint)
    mk = lambda idx, rng: GroupSnapshotStore(
        str(path), dict(fp), comm=None, index=idx, n_shards=2,
        part_range=rng, n_parts=8, recorder=recorder)
    return mk(0, (0, 4)), mk(1, (4, 8))


def _state(seed):
    rng = np.random.default_rng(seed)
    return {"x": rng.standard_normal((8, 3)), "rho": np.float64(seed),
            "it": np.int64(seed * 10)}


def _reader(path, fingerprint=None, elastic=False, recorder=None,
            n_shards=1):
    fp = dict(_FP2 if fingerprint is None else fingerprint)
    return GroupSnapshotStore(str(path), fp, comm=None, index=0,
                              n_shards=n_shards, part_range=(0, 8),
                              n_parts=8, recorder=recorder,
                              elastic=elastic)


def test_two_phase_commit_and_join(tmp_path):
    s0, s1 = _pair_stores(tmp_path)
    a = _state(1)
    s1.save(1, a)
    # rank 1 wrote its shard but only rank 0 publishes the marker: the
    # epoch is not committed yet and readers must not see it
    assert glob.glob(str(tmp_path / "snap_e*.p1.npz"))
    assert not glob.glob(str(tmp_path / "snap_COMMIT_*.json"))
    assert _reader(tmp_path).load(1) is None
    s0.save(1, a)
    (marker,) = glob.glob(str(tmp_path / "snap_COMMIT_*.json"))
    meta = json.loads(open(marker).read())
    assert meta["step"] == 1 and meta["n_shards"] == 2
    got = _reader(tmp_path).load(1)
    np.testing.assert_array_equal(got["x"], a["x"])    # re-joined rows
    assert got["rho"] == a["rho"] and got["it"] == a["it"]
    assert _reader(tmp_path).latest() == 1


def test_torn_epoch_falls_back_to_older_committed(tmp_path):
    s0, s1 = _pair_stores(tmp_path)
    a, b = _state(1), _state(2)
    s1.save(1, a)
    s0.save(1, a)       # epoch 0 committed
    s1.save(1, b)
    s0.save(1, b)       # epoch 1 committed
    # tear epoch 1: corrupt rank 1's shard after the fact (disk rot /
    # lost NFS write) -- the join must fall back to epoch 0, not mix
    shard = tmp_path / "snap_e000001.p1.npz"
    shard.write_bytes(b"not a zipfile")
    with pytest.warns(UserWarning, match="falling back"):
        got = _reader(tmp_path).load(1)
    np.testing.assert_array_equal(got["x"], a["x"])


def test_truncated_shard_set_falls_back(tmp_path):
    """Regression: a shard set that tiles contiguously from part 0 but
    ends SHORT of the marker's n_parts (e.g. leftover shards of a
    shrunk fleet matching an old marker's n_shards) must not restore a
    truncated global array — same named fallback as a torn epoch."""
    mk = lambda idx, rng: GroupSnapshotStore(
        str(tmp_path), dict(_FP2), comm=None, index=idx, n_shards=2,
        part_range=rng, n_parts=8)
    s0, s1 = mk(0, (0, 4)), mk(1, (4, 6))    # rows 6:8 never written
    a = _state(4)
    s1.save(1, a)
    s0.save(1, a)       # commits: every shard LANDED, but the set is short
    with pytest.warns(UserWarning, match="tile only 6 of 8 part rows"):
        assert _reader(tmp_path).load(1) is None


def test_uncommitted_save_stays_invisible(tmp_path):
    s0, s1 = _pair_stores(tmp_path)
    a = _state(1)
    s1.save(1, a)
    s0.save(1, a)       # epoch 0 committed
    b = _state(2)
    # rank 0 saves epoch 1 but the group min-agree reports a peer's
    # failed write: no marker may be published
    s0.comm = _ScriptedComm(script=[None, 0])
    s0.save(1, b)
    assert len(glob.glob(str(tmp_path / "snap_COMMIT_*.json"))) == 1
    got = _reader(tmp_path).load(1)
    np.testing.assert_array_equal(got["x"], a["x"])


def test_retention_prunes_committed_epochs_only(tmp_path, monkeypatch):
    """Regression (ISSUE 18 satellite): retention is routed through the
    commit markers.  With staggered writes — rank 1 already saved the
    next epoch while rank 0 has not committed it yet — rank 1's prune
    must keep both the newest COMMITTED epoch (the group's only agreed
    resume point) and its own in-flight shard, so pruning can never
    make two ranks resolve different newest snapshots."""
    monkeypatch.setenv("PCG_TPU_SNAP_KEEP", "1")
    s0, s1 = _pair_stores(tmp_path)
    a, b = _state(1), _state(2)
    s1.save(1, a)
    s0.save(1, a)                           # epoch 0 committed
    s1.save(1, b)                           # staggered: epoch 1 in flight
    # rank 1's prune ran with keep=1 while epoch 1 is uncommitted: the
    # committed epoch 0 AND the in-flight epoch-1 shard both survive
    assert os.path.exists(tmp_path / "snap_e000000.p1.npz")
    assert os.path.exists(tmp_path / "snap_e000001.p1.npz")
    assert _reader(tmp_path).load(1) is not None
    s0.save(1, b)                           # epoch 1 commits; 0 prunable
    assert not glob.glob(str(tmp_path / "snap_e000000.*"))
    assert not os.path.exists(tmp_path / "snap_COMMIT_e000000.json")
    got = _reader(tmp_path).load(1)
    np.testing.assert_array_equal(got["x"], b["x"])


def test_elastic_reader_named_event_and_refusal(tmp_path):
    s0, s1 = _pair_stores(tmp_path)
    a = _state(3)
    s1.save(1, a)
    s0.save(1, a)
    # a 1-process reader of the 2-process epoch: refused by default ...
    with pytest.raises(ValueError, match="n_procs"):
        _reader(tmp_path, {"n_procs": 1, "tol": 1e-8}).load(1)
    # ... but the armed elastic path re-joins it and names the event
    cap = _Cap()
    rec = MetricsRecorder(sinks=[cap])
    got = _reader(tmp_path, {"n_procs": 1, "tol": 1e-8}, elastic=True,
                  recorder=rec).load(1)
    np.testing.assert_array_equal(got["x"], a["x"])
    (ev,) = _kinds(cap, "elastic_resume")
    assert ev["from_procs"] == 2 and ev["to_procs"] == 1
    assert rec.counters["resilience.elastic_resume"] == 1
    # elastic only forgives the process count, nothing else
    with pytest.raises(ValueError):
        _reader(tmp_path, {"n_procs": 1, "tol": 1e-6},
                elastic=True).load(1)


def test_discard_drops_markers_then_shards(tmp_path):
    s0, s1 = _pair_stores(tmp_path)
    s1.save(1, _state(1))
    s0.save(1, _state(1))
    s0.discard(1)
    assert not glob.glob(str(tmp_path / "snap_COMMIT_*.json"))
    assert not glob.glob(str(tmp_path / "snap_e*.npz"))


def test_new_event_kinds_in_schema():
    from pcg_mpi_solver_tpu.obs.schema import EVENT_KINDS

    assert EVENT_KINDS["collective_timeout"] == ("label", "deadline_s",
                                                 "suspect")
    assert EVENT_KINDS["snapshot_epoch"] == ("epoch", "step", "shards",
                                             "committed")
    assert EVENT_KINDS["elastic_resume"] == ("from_procs", "to_procs",
                                             "prefix")


# ----------------------------------------------------------------------
# E2e: real two-process jax.distributed runs
# ----------------------------------------------------------------------

_CHILD_FT = r"""
import hashlib, os, sys, time

MODE = sys.argv[4]            # ref | kill | resume
scratch = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
if MODE != "ref":
    # the ref run stays DEFAULT-CONFIG (no watchdog knob): the group
    # consensus + commit-marker protocol must hold without it, and the
    # resume run's bit-identical digest proves it did
    os.environ["PCG_TPU_COLLECTIVE_DEADLINE_S"] = "5"
os.environ["PCG_TPU_FLIGHT_HEARTBEAT_S"] = "0.2"
if MODE == "kill":
    os.environ["PCG_TPU_FAULTS"] = "kill@rank:1:3"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np
from pcg_mpi_solver_tpu.parallel.distributed import (init_distributed,
                                                     make_global_mesh)

pid = init_distributed(coordinator_address=sys.argv[1], num_processes=2,
                       process_id=int(sys.argv[2]))

from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.resilience import DeadPeerError, SimulatedKill
from pcg_mpi_solver_tpu.solver import Solver

cfg = RunConfig(scratch_path=scratch, run_id="ft", snapshot_every=1,
                flight_path=os.path.join(scratch, "flight.jsonl"),
                solver=SolverConfig(tol=1e-8, max_iter=500,
                                    iters_per_dispatch=12, trace_resid=32),
                time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]))
s = Solver(make_mh_test_model("general"), cfg, mesh=make_global_mesh(),
           n_parts=8, backend="general")

t0 = time.monotonic()
try:
    res = s.solve(resume=(MODE == "resume"))[-1]
    tr = s.last_trace
    u = np.ascontiguousarray(np.asarray(s.displacement_global()))
    digest = hashlib.sha256(
        np.ascontiguousarray(np.asarray(tr.normr, np.float64)).tobytes()
        + u.tobytes()).hexdigest()[:16]
    print(f"RESULT {pid} outcome=done flag={res.flag} iters={res.iters} "
          f"relres={float(res.relres).hex()} trace_n={tr.n_recorded} "
          f"digest={digest}", flush=True)
    sys.exit(0)       # ordered shutdown: the group is still alive
except SimulatedKill:
    # abrupt process death: exit with no shutdown handshakes, exactly
    # like a SIGKILLed worker -- the survivor must detect it by deadline
    print(f"RESULT {pid} outcome=killed ckpt={cfg.checkpoint_path}",
          flush=True)
    os._exit(0)
except DeadPeerError as e:
    print(f"RESULT {pid} outcome=deadpeer waited={time.monotonic()-t0:.1f} "
          f"msg={str(e)!r}", flush=True)
    os._exit(0)
"""


_MULTIPROC = pytest.mark.skipif(
    os.environ.get("PCG_TPU_SKIP_MULTIPROC") == "1",
    reason="multi-process test disabled")


def _tails(results):
    """RESULT payloads with the rank prefix stripped."""
    return [r.split(" ", 2)[2] for r in results]


@_MULTIPROC
def test_dead_peer_named_and_resume_scalar(tmp_path):
    """ISSUE 18 acceptance: kill rank 1 mid-Krylov -> the survivor
    raises DeadPeerError naming process 1 within the deadline; a
    same-count relaunch resumes from the committed epoch bit-identically
    (history + trace ring + solution digest) vs an uninterrupted run."""
    scratch = tmp_path / "s"
    ref = _run_multiproc(tmp_path, _CHILD_FT, 2, [str(scratch / "ref"),
                                                  "ref"])
    assert all("outcome=done flag=0" in r for r in ref)

    kill = _run_multiproc(tmp_path, _CHILD_FT, 2, [str(scratch / "run"),
                                                   "kill"])
    by = {int(r.split()[1]): r for r in kill}
    assert "outcome=killed" in by[1]
    assert "outcome=deadpeer" in by[0], by[0]
    assert "suspected dead peer: process 1" in by[0]
    waited = float(by[0].split("waited=")[1].split()[0])
    assert waited < 60.0
    # the dead fleet left committed epochs behind
    ckpt = by[1].split("ckpt=")[1].strip()
    assert glob.glob(os.path.join(ckpt, "snap_COMMIT_e*.json"))

    res = _run_multiproc(tmp_path, _CHILD_FT, 2, [str(scratch / "run"),
                                                  "resume"])
    assert all("outcome=done flag=0" in r for r in res)
    # every rank of the resumed run reports the exact reference payload
    assert set(_tails(res)) == set(_tails(ref))
    # completion discarded the in-flight epochs
    assert not glob.glob(os.path.join(ckpt, "snap_COMMIT_e*.json"))


_CHILD_FT_MANY = r"""
import hashlib, os, sys, time

MODE = sys.argv[4]            # ref | kill | resume
scratch = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
if MODE != "ref":
    # ref stays default-config: consensus/commit must hold without the
    # watchdog knob (see the scalar child)
    os.environ["PCG_TPU_COLLECTIVE_DEADLINE_S"] = "5"
os.environ["PCG_TPU_FLIGHT_HEARTBEAT_S"] = "0.2"
if MODE == "kill":
    os.environ["PCG_TPU_FAULTS"] = "kill@rank:1:2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np
from pcg_mpi_solver_tpu.parallel.distributed import (init_distributed,
                                                     make_global_mesh)

pid = init_distributed(coordinator_address=sys.argv[1], num_processes=2,
                       process_id=int(sys.argv[2]))

from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.resilience import DeadPeerError, SimulatedKill
from pcg_mpi_solver_tpu.solver import Solver

model = make_mh_test_model("general")
cfg = RunConfig(scratch_path=scratch, run_id="ftm", snapshot_every=1,
                flight_path=os.path.join(scratch, "flight.jsonl"),
                solver=SolverConfig(tol=1e-8, max_iter=500,
                                    iters_per_dispatch=12),
                time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]))
s = Solver(model, cfg, mesh=make_global_mesh(), n_parts=8,
           backend="general")

F = np.asarray(model.F)
rng = np.random.default_rng(5)
hard = np.zeros(model.n_dof)
eff = np.asarray(model.dof_eff)
hard[eff] = rng.standard_normal(eff.size)
fb = np.stack([F, hard], axis=-1)

t0 = time.monotonic()
try:
    res = s.solve_many(fb, resume=(MODE == "resume"))
    print(f"RESULT {pid} outcome=done flags={[int(f) for f in res.flags]} "
          f"iters={np.asarray(res.iters).tolist()} "
          f"relres={[float(v).hex() for v in np.asarray(res.relres)]}",
          flush=True)
    sys.exit(0)       # ordered shutdown: the group is still alive
except SimulatedKill:
    print(f"RESULT {pid} outcome=killed ckpt={cfg.checkpoint_path}",
          flush=True)
    os._exit(0)
except DeadPeerError as e:
    print(f"RESULT {pid} outcome=deadpeer waited={time.monotonic()-t0:.1f} "
          f"msg={str(e)!r}", flush=True)
    os._exit(0)
"""


@_MULTIPROC
def test_dead_peer_and_resume_many(tmp_path):
    """The blocked multi-RHS twin of the scalar drill: rank 1 killed at
    a blocked chunk boundary -> DeadPeerError on the survivor; resume
    reproduces the uninterrupted per-column flags/iters/relres."""
    scratch = tmp_path / "s"
    ref = _run_multiproc(tmp_path, _CHILD_FT_MANY, 2,
                         [str(scratch / "ref"), "ref"])
    assert all("outcome=done flags=[0, 0]" in r for r in ref)

    kill = _run_multiproc(tmp_path, _CHILD_FT_MANY, 2,
                          [str(scratch / "run"), "kill"])
    by = {int(r.split()[1]): r for r in kill}
    assert "outcome=killed" in by[1]
    assert "outcome=deadpeer" in by[0], by[0]
    assert "suspected dead peer: process 1" in by[0]

    res = _run_multiproc(tmp_path, _CHILD_FT_MANY, 2,
                         [str(scratch / "run"), "resume"])
    assert set(_tails(res)) == set(_tails(ref))


_CHILD_ELASTIC = r"""
import os, sys

scratch = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
os.environ["PCG_TPU_COLLECTIVE_DEADLINE_S"] = "5"
os.environ["PCG_TPU_FAULTS"] = "kill@3"      # every rank dies at boundary 3
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from pcg_mpi_solver_tpu.parallel.distributed import (init_distributed,
                                                     make_global_mesh)

pid = init_distributed(coordinator_address=sys.argv[1], num_processes=2,
                       process_id=int(sys.argv[2]))

from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.resilience import SimulatedKill
from pcg_mpi_solver_tpu.solver import Solver

cfg = RunConfig(scratch_path=scratch, run_id="el", snapshot_every=1,
                solver=SolverConfig(tol=1e-8, max_iter=500,
                                    iters_per_dispatch=12, trace_resid=32),
                time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]))
s = Solver(make_mh_test_model("general"), cfg, mesh=make_global_mesh(),
           n_parts=8, backend="general")
try:
    s.solve()
    print(f"RESULT {pid} outcome=done", flush=True)
except SimulatedKill:
    print(f"RESULT {pid} outcome=killed ckpt={cfg.checkpoint_path}",
          flush=True)
os._exit(0)
"""


@_MULTIPROC
def test_elastic_resume_two_to_one(tmp_path):
    """A committed 2-process epoch resumes on ONE process:
    Solver.resume_elastic re-joins the shards, names the n_procs
    mismatch as an ``elastic_resume`` event, and finishes with the
    uninterrupted solve's answer."""
    from pcg_mpi_solver_tpu import (RunConfig, SolverConfig,
                                    TimeHistoryConfig)
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver import Solver

    scratch = tmp_path / "s"
    kill = _run_multiproc(tmp_path, _CHILD_ELASTIC, 2, [str(scratch)])
    assert all("outcome=killed" in r for r in kill)
    ckpt = kill[0].split("ckpt=")[1].strip()
    assert glob.glob(os.path.join(ckpt, "snap_COMMIT_e*.json"))

    model = make_mh_test_model("general")

    def _cfg(run_id, snap):
        return RunConfig(scratch_path=str(tmp_path / "local"),
                         run_id=run_id, snapshot_every=snap,
                         solver=SolverConfig(tol=1e-8, max_iter=500,
                                             iters_per_dispatch=12,
                                             trace_resid=32),
                         time_history=TimeHistoryConfig(
                             time_step_delta=[0.0, 1.0]))

    sref = Solver(model, _cfg("ref", 0), mesh=make_mesh(8), n_parts=8)
    ref = sref.solve()[-1]
    assert ref.flag == 0

    cap = _Cap()
    rec = MetricsRecorder(sinks=[cap])
    sel = Solver(model, _cfg("el1", 1), mesh=make_mesh(8), n_parts=8,
                 recorder=rec)
    res = sel.resume_elastic(ckpt)[-1]
    assert res.flag == 0
    # the elastic path was actually taken, loudly
    assert rec.counters["resilience.elastic_resume"] >= 1
    evs = _kinds(cap, "elastic_resume")
    assert evs and evs[0]["from_procs"] == 2 and evs[0]["to_procs"] == 1
    assert any(e.get("op") == "restore"
               for e in _kinds(cap, "snapshot_epoch"))
    # and it finished with the uninterrupted answer.  The shard re-join
    # is exact, but the resumed iterations run 1-process reduction
    # order vs the reference's — same ~1e-7 arithmetic skew the
    # existing 2-vs-1-process parity test tolerates, on top of the
    # tol=1e-8 convergence floor.
    assert abs(res.iters - ref.iters) <= 1
    assert np.isclose(res.relres, ref.relres, rtol=1e-6)
    np.testing.assert_allclose(sel.displacement_global(),
                               sref.displacement_global(),
                               rtol=1e-4, atol=1e-8)
