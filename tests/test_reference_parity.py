"""Cross-implementation parity: the REFERENCE implementation's own
pipeline (ingest -> metis N=1 shortcut -> partition -> PCG solve) runs
single-rank under tools/mpi_shim, and this framework solves the SAME
model the reference's partitioner consumed — iteration counts and
residuals must agree.

This is the strongest form of the BASELINE.json contract ("identical
iteration count and residual"): not a golden number, the reference's
actual code executed side by side.  Skipped automatically when the
reference checkout is unavailable."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = os.environ.get("PCG_REFERENCE_PATH", "/root/reference")


@pytest.mark.skipif(
    not os.path.isdir(os.path.join(REFERENCE, "src", "solver")),
    reason="reference checkout not available")
@pytest.mark.parametrize("model,n,modes", [
    ("cube", 10, ["Full", "Delaunay"]),
    ("octree", 2, ["Boundary", "MidSlices"]),
])
def test_reference_pipeline_iteration_parity(tmp_path, model, n, modes):
    """cube: the heterogeneous single-type path with Full-mode export and
    Delaunay (the reference's point-cloud tetrahedralization,
    export_vtk.py:178-215 — byte-identical arrays expected since both
    sides run the same deterministic qhull on the same coordinates);
    octree: the reference's actual problem class — multiple pattern types
    WITH sign vectors, solved here on the hybrid level-grid backend —
    with its Boundary (PolysFlat incidence) and MidSlices (plane
    selection) export modes, all served from the one solve."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "run_reference_baseline.py"),
         "--model", model, "--n", str(n), "--compare", "--speedtest", "0",
         "--export-compare", "--export-mode"] + modes
        + ["--scratch", str(tmp_path)],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    ref, ours = result["reference"], result["this_framework_cpu"]
    assert ref["flag"] == 0 and ours["flag"] == 0
    assert ref["relres"] <= 1e-7 and ours["relres"] <= 1e-7
    # MATLAB-pcg-compatible semantics on both sides: same Krylov path
    assert abs(ours["iters"] - ref["iters"]) <= 1, (ours["iters"],
                                                    ref["iters"])
    # and the same solution, via the reference's own exported U frame
    assert ours["solution_max_rel_diff"] < 1e-5, ours
    # .vtu content parity: identical face geometry, U to solver tolerance
    for mode in modes:
        vp = result["vtu_parity"][mode]
        assert vp["faces_match"], vp
        assert vp["n_cells_ref"] == vp["n_cells_ours"], vp
        assert vp["points_missing_in_ours"] == 0, vp
        assert vp["u_max_rel_diff"] < 1e-6, vp
        if mode in ("Full", "Delaunay"):
            # arrays byte-identical, not just geometry-equal
            assert vp["points_max_abs_diff"] == 0.0, vp
            assert vp["connectivity_max_diff"] == 0, vp
            assert vp["offsets_max_diff"] == 0, vp


@pytest.mark.skipif(
    not os.path.isdir(os.path.join(REFERENCE, "src", "solver")),
    reason="reference checkout not available")
@pytest.mark.parametrize("model,n,level,incl", [
    ("cube", 10, 2, 2),
    ("octree", 4, 2, 2),
    # deep grading: level-3 with 6 inclusions -> 77 simultaneous
    # edge+face hanging-node pattern types (the reference's <=144-type
    # regime, partition_mesh.py:1074) through the full 8-rank pipeline
    ("octree", 4, 3, 6),
])
def test_reference_multirank_iteration_parity(tmp_path, model, n, level,
                                              incl):
    """The reference at 8 REAL ranks (tools/mpi_shim multi-rank: router-
    backed p2p/collectives, mmap shared windows, concurrent MPI-IO):
    run_metis builds a genuine k-way dual-graph partition (mgmetis
    stand-in over the framework's C++ partitioner), partition_mesh runs
    its AABB-Allgather neighbor discovery + Isend/Recv halo construction
    at 4 workers (partition_mesh.py:674-921), and pcg_solver exchanges
    halos across 8 processes per iteration (pcg_solver.py:317-334).
    Iteration counts, residuals and the exported solution must match
    this framework on the same model."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "run_reference_baseline.py"),
         "--model", model, "--n", str(n), "--level", str(level),
         "--incl", str(incl), "--ranks", "8", "--compare",
         "--speedtest", "0", "--scratch", str(tmp_path)],
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    ref, ours = result["reference"], result["this_framework_cpu"]
    assert ref["ranks"] == 8
    assert ref["flag"] == 0 and ours["flag"] == 0
    assert ref["relres"] <= 1e-7 and ours["relres"] <= 1e-7
    assert abs(ours["iters"] - ref["iters"]) <= 1, (ours["iters"],
                                                    ref["iters"])
    # solution via the reference's own 8-rank parallel MPI-IO export.
    # Looser than the single-rank bound: at 8 ranks the reference's
    # reduction order differs, so two solves that EACH satisfy
    # relres <= 1e-7 can differ per dof on near-zero dofs under the
    # elementwise-relative metric (observed 1.6e-5 on the octree with
    # matching iteration counts; 1.3e-4 when a summation-order change —
    # the gather-combine — converges one iteration apart at 146 vs 147).
    # The bound is tolerance noise, not operator error: a wrong matvec
    # or halo shows up at O(1) here.
    assert ours["solution_max_rel_diff"] < 1e-3, ours


@pytest.mark.skipif(
    not os.path.isdir(os.path.join(REFERENCE, "src", "solver")),
    reason="reference checkout not available")
def test_reference_nonlocal_weight_parity(tmp_path):
    """The reference's nonlocal-stress subsystem
    (config_NonlocalNeighbours, partition_mesh.py:1000-1299) as an
    oracle: its per-partition Gaussian weight csr — built by its own
    code at 4 REAL ranks (AABB broadcast, element-id Isend/Recv, box
    search) — composed to a global operator must match this framework's
    ops/nonlocal_stress.py exactly (same sparsity, values to 1e-12).

    The reference's own NonLocStressParam parsing is commented out
    (partition_mesh.py:515-523, a latent defect like its Se.mat strain
    path); tools/ref_nonlocal_wrapper.py injects exactly what that
    parser would produce and runs the reference's main sequence
    otherwise unmodified."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "run_reference_nonlocal.py"),
         "--n", "8", "--ranks", "4", "--scratch", str(tmp_path)],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["parity"] == "PASS", result
    assert result["pattern_only_ref"] == 0 == result["pattern_only_ours"]
    assert result["max_abs_diff"] < 1e-12
