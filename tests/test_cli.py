"""CLI end-to-end: ingest -> partition -> solve -> export on a synthetic
model written in the reference's MDF format."""

import os
import shutil

import numpy as np
import pytest

from pcg_mpi_solver_tpu.cli import main
from pcg_mpi_solver_tpu.models.mdf import write_mdf
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model


def test_cli_full_pipeline(tmp_path, capsys):
    model = make_cube_model(4, 4, 4, load="traction", heterogeneous=True)
    src = tmp_path / "src"
    write_mdf(model, str(src))
    archive = shutil.make_archive(str(tmp_path / "cube"), "zip", src)
    scratch = str(tmp_path / "scratch")

    main(["ingest", archive, scratch])
    out = capsys.readouterr().out
    assert f">dofs:      {model.n_dof}" in out

    main(["partition", scratch, "2"])
    assert os.path.exists(f"{scratch}/ModelData/MeshPart_2.npy")

    main(["solve", scratch, "1", "--n-parts", "2", "--tol", "1e-8",
          "--precision", "direct"])
    out = capsys.readouterr().out
    assert "flag=0" in out and ">success!" in out
    assert os.path.exists(f"{scratch}/Results_Run1/ResVecData/U_1.npy")

    main(["export", scratch, "1", "U", "Full"])
    out = capsys.readouterr().out
    assert "vtu files" in out
    assert os.path.exists(f"{scratch}/Results_Run1/VTKs/VTKInfo.txt")


def test_cli_demo(tmp_path, capsys):
    main(["demo", "--nx", "4", "--scratch", str(tmp_path / "s"),
          "--tol", "1e-7", "--precision", "direct"])
    out = capsys.readouterr().out
    assert ">success!" in out and "flag=0" in out


def test_cli_poisson_demo(tmp_path, capsys):
    main(["demo", "--poisson", "--nx", "4", "--scratch", str(tmp_path / "s"),
          "--tol", "1e-8", "--precision", "direct"])
    out = capsys.readouterr().out
    assert ">success!" in out and "flag=0" in out and "scalar" in out


def test_cli_speed_test_no_exports(tmp_path, capsys):
    model = make_cube_model(4, 4, 4)
    src = tmp_path / "src"
    write_mdf(model, str(src))
    archive = shutil.make_archive(str(tmp_path / "cube"), "zip", src)
    scratch = str(tmp_path / "scratch")
    main(["ingest", archive, scratch])
    main(["solve", scratch, "2", "--n-parts", "1", "--speed-test",
          "--precision", "direct"])
    capsys.readouterr()
    assert not os.path.exists(f"{scratch}/Results_Run2_SpeedTest/ResVecData/U_1.npy")

def test_cli_octree_demo(tmp_path, capsys):
    main(["demo", "--octree", "--nx", "2", "--max-level", "2",
          "--scratch", str(tmp_path / "sc"), "--max-iter", "2000"])
    out = capsys.readouterr().out
    assert "pattern types" in out
    assert "[hybrid backend]" in out
    assert "flag=0" in out and ">success!" in out


def test_cli_solve_backend_flag(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("PCG_TPU_ENABLE_HYBRID", "1")   # auto->hybrid gate
    from pcg_mpi_solver_tpu.models.octree import make_octree_model

    model = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3)
    src = tmp_path / "src"
    write_mdf(model, str(src))
    archive = shutil.make_archive(str(tmp_path / "ot"), "zip", src)
    scratch = str(tmp_path / "scratch")
    main(["ingest", archive, scratch])
    # sidecar survives ingest -> auto backend resolves hybrid; the flag
    # can force the general path
    main(["solve", scratch, "3", "--n-parts", "4", "--precision", "direct"])
    out = capsys.readouterr().out
    assert ">backend: hybrid" in out and "flag=0" in out
    main(["solve", scratch, "4", "--n-parts", "4", "--backend", "general",
          "--precision", "direct"])
    out = capsys.readouterr().out
    assert ">backend: general" in out and "flag=0" in out
