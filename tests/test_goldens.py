"""Pinned golden numbers for fixed flagship models (VERDICT round 1,
missing #5): the reference's committed notebook outputs play this role
(solver_demo.ipynb cell 12); here the goldens are asserted in CI so any
numerics regression fails a test.  A deliberate algorithm change that moves
one of these must re-pin it with justification in the commit message.

The octree golden lives in tests/test_octree.py (same pattern)."""

import numpy as np

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.driver import Solver

# Cube 6x5x5 (h=0.5, nu=0.3, heterogeneous seed 0), tol=1e-8, Jacobi,
# 4 parts on 4 devices.  Pinned at round 2.
GOLDEN_CUBE = {
    "direct": {"iters": 115, "checksum": 2535.2226603195363},
    "mixed": {"iters": 168, "checksum": 2535.222664843344},
}


def _solve(mode):
    model = make_cube_model(6, 5, 5, h=0.5, nu=0.3, heterogeneous=True, seed=0)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000, precision_mode=mode),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    res = s.step(1.0)
    return res, float(np.abs(s.displacement_global()).sum())


def test_golden_cube_direct():
    res, checksum = _solve("direct")
    g = GOLDEN_CUBE["direct"]
    assert res.flag == 0
    assert res.relres <= 1e-8
    assert abs(res.iters - g["iters"]) <= 1, res.iters
    assert np.isclose(checksum, g["checksum"], rtol=1e-6), checksum


def test_golden_cube_mixed():
    """Mixed precision must land on the same solution (checksum agrees with
    the direct golden to ~tol) at its own pinned iteration count."""
    res, checksum = _solve("mixed")
    g = GOLDEN_CUBE["mixed"]
    assert res.flag == 0
    assert abs(res.iters - g["iters"]) <= 2, res.iters
    assert np.isclose(checksum, g["checksum"], rtol=1e-6), checksum
    assert np.isclose(checksum, GOLDEN_CUBE["direct"]["checksum"], rtol=1e-7)
