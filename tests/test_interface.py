"""Cohesive interface element tests: matvec/diag vs the dense oracle (with
springs crossing partition boundaries) and the glued-blocks physics check
(reference builds interface scaffolding at partition_mesh.py:603-650 but
never solves with it; here it is a live capability)."""

import jax.numpy as jnp
import numpy as np
import pytest

from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import (
    make_cube_model,
    make_glued_blocks_model,
)
from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
from pcg_mpi_solver_tpu.parallel.partition import partition_model
from pcg_mpi_solver_tpu.solver.driver import Solver
from pcg_mpi_solver_tpu.solver.numpy_ref import NumpyRefSolver

from tests.test_matvec import global_to_parts, parts_to_global


def test_interface_springs_flatten():
    model = make_glued_blocks_model(2, 2, 2, 2, E=5.0, penalty=100.0)
    sa, sb, sk, adj = model.interface_springs()
    n_ie = len(model.intfc_elems)
    assert n_ie == 4                      # 2x2 interface faces
    assert len(sa) == n_ie * 4 * 3        # 4 pairs x 3 components
    # coincident node pairs: same coordinates, different ids
    na, nb = sa // 3, sb // 3
    np.testing.assert_allclose(model.node_coords[na], model.node_coords[nb])
    assert np.all(na != nb)
    # normal components stiffer iff kt_factor < 1; here kt=kn
    assert np.all(sk > 0)


@pytest.mark.parametrize("n_parts", [1, 4])
def test_matvec_with_springs_vs_dense(n_parts):
    """Springs cross the partition boundary when the two blocks land in
    different parts; the psum interface assembly must still reproduce the
    dense operator exactly."""
    model = make_glued_blocks_model(2, 3, 2, 2, E=3.0, penalty=50.0,
                                    kt_factor=0.5)
    # force a partition that splits the blocks (and hence the springs)
    elem_part = None
    if n_parts > 1:
        elem_part = (model.sctrs[:, 0] > 2.0).astype(np.int32) * (n_parts // 2)
        elem_part += (model.sctrs[:, 1] > 1.0).astype(np.int32)
    pm = partition_model(model, n_parts, elem_part=elem_part)
    assert pm.spr_a is not None
    data = device_data(pm)
    ops = Ops.from_model(pm)

    rng = np.random.default_rng(5)
    x = rng.normal(size=model.n_dof)
    y = ops.matvec(data, jnp.asarray(global_to_parts(pm, x)))
    y_ref = model.assemble_csr() @ x
    np.testing.assert_allclose(parts_to_global(pm, y), y_ref,
                               rtol=1e-10, atol=1e-10)

    d = ops.diag(data)
    np.testing.assert_allclose(parts_to_global(pm, d), model.assemble_diag(),
                               rtol=1e-12)


def test_numpy_ref_includes_springs():
    model = make_glued_blocks_model(2, 2, 2, 2, penalty=20.0)
    ref = NumpyRefSolver(model)
    x = np.random.default_rng(0).normal(size=model.n_dof)
    np.testing.assert_allclose(ref.matvec(x), model.assemble_csr() @ x,
                               rtol=1e-10, atol=1e-10)
    np.testing.assert_allclose(ref.diag(), model.assemble_diag(), rtol=1e-12)


def test_glued_blocks_approach_monolithic():
    """With a stiff penalty the glued 2+2 block must deform like the
    monolithic length-4 block; with a soft interface it must be more
    compliant."""
    ny = nz = 2
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-10, max_iter=4000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                       export_flag=False),
    )

    mono = make_cube_model(4, ny, nz, E=10.0, load="traction", load_value=1.0)
    s0 = Solver(mono, cfg, backend="general")
    s0.solve()
    tip0 = s0.displacement_global()[0::3].max()

    tips = {}
    for pen in (1e4, 1e-1):
        glued = make_glued_blocks_model(2, 2, ny, nz, E=10.0, load_value=1.0,
                                        penalty=pen)
        s = Solver(glued, cfg)
        s.solve()
        tips[pen] = s.displacement_global()[0::3].max()

    assert tips[1e4] == pytest.approx(tip0, rel=2e-3)
    assert tips[1e-1] > 1.5 * tip0


def test_mdf_roundtrip_preserves_interfaces(tmp_path):
    """write_mdf/read_mdf must carry cohesive interfaces (Intfc.npz schema
    extension) — losing them silently would leave block b unconstrained."""
    from pcg_mpi_solver_tpu.models.mdf import read_mdf, write_mdf

    model = make_glued_blocks_model(2, 2, 2, 2, penalty=33.0, kt_factor=0.25)
    back = read_mdf(write_mdf(model, str(tmp_path / "mdf")))
    assert back.intfc_elems is not None
    assert len(back.intfc_elems) == len(model.intfc_elems)
    for a, b in zip(model.intfc_elems, back.intfc_elems):
        np.testing.assert_array_equal(a["NodeIdList"], b["NodeIdList"])
        assert (a["adj_elem"], a["kn"], a["kt"], a["area"], a["normal_axis"]) \
            == (b["adj_elem"], b["kn"], b["kt"], b["area"], b["normal_axis"])
    x = np.random.default_rng(1).normal(size=model.n_dof)
    np.testing.assert_allclose(back.assemble_csr() @ x,
                               model.assemble_csr() @ x, rtol=1e-12)
    # NonLocStressParam survives the MatProp round-trip
    model.mat_prop[0]["NonLocStressParam"] = {"Lc": 5.0}
    back2 = read_mdf(write_mdf(model, str(tmp_path / "mdf")))
    assert back2.mat_prop[0]["NonLocStressParam"]["Lc"] == 5.0
    # overwriting with an interface-free model must purge the stale Intfc.npz
    cube = make_cube_model(2, 2, 2)
    back3 = read_mdf(write_mdf(cube, str(tmp_path / "mdf")))
    assert back3.intfc_elems is None


def test_glued_solve_matches_numpy_ref():
    model = make_glued_blocks_model(2, 2, 3, 2, E=7.0, load_value=0.5,
                                    penalty=10.0)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-10, max_iter=4000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                       export_flag=False),
    )
    s = Solver(model, cfg, n_parts=8)
    s.solve()
    ref = NumpyRefSolver(model).solve(tol=1e-10, max_iter=4000)
    np.testing.assert_allclose(s.displacement_global(), ref.u,
                               rtol=1e-6, atol=1e-9)
