"""MDF (reference model format) round-trip and solve-equivalence."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig
from pcg_mpi_solver_tpu.models.mdf import ingest_archive, read_mdf, write_mdf
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.driver import Solver


def test_mdf_roundtrip(tmp_path):
    model = make_cube_model(4, 3, 3, h=0.5, E=2.0, nu=0.3, n_types=2,
                            heterogeneous=True)
    write_mdf(model, str(tmp_path / "MDF"))
    m2 = read_mdf(str(tmp_path / "MDF"))

    assert (m2.n_elem, m2.n_node, m2.n_dof) == (model.n_elem, model.n_node, model.n_dof)
    np.testing.assert_array_equal(m2.elem_nodes_flat, model.elem_nodes_flat)
    np.testing.assert_array_equal(m2.elem_dofs_flat, model.elem_dofs_flat)
    np.testing.assert_array_equal(m2.elem_type, model.elem_type)
    np.testing.assert_allclose(m2.ck, model.ck)
    np.testing.assert_allclose(m2.F, model.F)
    np.testing.assert_array_equal(m2.fixed_dof, model.fixed_dof)
    np.testing.assert_allclose(m2.node_coords, model.node_coords)
    np.testing.assert_allclose(m2.elem_lib[0]["Ke"], model.elem_lib[0]["Ke"])
    np.testing.assert_allclose(m2.elem_lib[0]["Se"], model.elem_lib[0]["Se"])
    assert m2.mat_prop[0]["E"] == model.mat_prop[0]["E"]
    assert m2.mat_prop[1]["E"] == model.mat_prop[1]["E"]

    # same stiffness operator
    x = np.random.default_rng(0).normal(size=model.n_dof)
    np.testing.assert_allclose(m2.assemble_csr() @ x, model.assemble_csr() @ x,
                               rtol=1e-12)


def test_mdf_solve_equivalence(tmp_path):
    """A model read back from MDF solves to the same displacements."""
    model = make_cube_model(4, 4, 4, load="dirichlet", heterogeneous=True)
    write_mdf(model, str(tmp_path / "MDF"))
    m2 = read_mdf(str(tmp_path / "MDF"))
    cfg = RunConfig(solver=SolverConfig(tol=1e-10, max_iter=2000))
    mesh = make_mesh(2)
    s1 = Solver(model, cfg, mesh=mesh, n_parts=2, backend="general")
    s1.step(1.0)
    s2 = Solver(m2, cfg, mesh=mesh, n_parts=2, backend="general")
    s2.step(1.0)
    np.testing.assert_allclose(s1.displacement_global(),
                               s2.displacement_global(), rtol=1e-10)


def test_ingest_archive(tmp_path):
    import shutil

    model = make_cube_model(3, 3, 3)
    src = tmp_path / "src"
    write_mdf(model, str(src))
    archive = shutil.make_archive(str(tmp_path / "cube_model"), "zip", src)
    mdf = ingest_archive(archive, str(tmp_path / "scratch"))
    m2 = read_mdf(mdf)
    assert m2.n_elem == model.n_elem

def test_mdf_roundtrip_fastpath_sidecars(tmp_path, monkeypatch):
    monkeypatch.setenv("PCG_TPU_ENABLE_HYBRID", "1")   # auto->hybrid gate
    """grid/octree metadata survives the MDF round trip, so re-ingested
    models keep their structured/hybrid backend eligibility."""
    from pcg_mpi_solver_tpu.models.octree import make_octree_model

    cube = make_cube_model(4, 3, 3)
    m2 = read_mdf(write_mdf(cube, str(tmp_path / "cube")))
    assert m2.grid == cube.grid

    ot = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3)
    m3 = read_mdf(write_mdf(ot, str(tmp_path / "ot")))
    assert m3.octree is not None
    assert m3.octree["brick_type"] == ot.octree["brick_type"]
    np.testing.assert_array_equal(m3.octree["leaves"], ot.octree["leaves"])
    np.testing.assert_array_equal(m3.octree["node_keys"],
                                  ot.octree["node_keys"])
    np.testing.assert_array_equal(m3.octree["brick_corners"],
                                  ot.octree["brick_corners"])
    assert m3.octree["strides"] == ot.octree["strides"]

    # the re-read model solves on the hybrid backend like the original
    from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
    from pcg_mpi_solver_tpu.solver import Solver

    cfg = RunConfig(solver=SolverConfig(tol=1e-8, max_iter=2000),
                    time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]))
    s1 = Solver(ot, cfg, mesh=make_mesh(4), n_parts=4)
    s2 = Solver(m3, cfg, mesh=make_mesh(4), n_parts=4)
    assert s1.backend == s2.backend == "hybrid"
    r1, r2 = s1.step(1.0), s2.step(1.0)
    assert r1.flag == 0 and r2.flag == 0
    assert abs(r1.iters - r2.iters) <= 1
    np.testing.assert_allclose(s1.displacement_global(),
                               s2.displacement_global(), rtol=1e-8)
