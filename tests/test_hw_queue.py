"""Contract tests for the hardware-queue tooling (tools/hw_session.py,
tools/hw_v9_ab.py): the session-log format run_step writes is parsed by
the wave queues to make engage/skip decisions, so the coupling needs a
test.  Pure subprocess/log logic — no accelerator, no solver."""

import os
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_run_step_log_matches_ab_parser(tmp_path, monkeypatch):
    """run_step's start/done line format must stay parseable by
    tools/hw_v9_ab._parse_ab — the marker anchors on the START line and
    must not match the trailing 'done:' line."""
    from tools import hw_session
    from tools.hw_v9_ab import _parse_ab

    fake = tmp_path / "fake_ab.py"
    fake.write_text(textwrap.dedent("""\
        print("10328853 dofs on FakeDevice")
        print("xla (gse):      13.741 ms/matvec")
        print("pallas v9 C=8:    3.100 ms/matvec  (vs xla  4.43x, "
              "maxrelerr 1.2e-07)")
    """))
    log = tmp_path / "log.txt"
    monkeypatch.setattr(hw_session, "_last_step_ok", True)
    hw_session.run_step(str(log), "matvec A/B v9", [str(fake)],
                        timeout=60, gate_s=0)
    gse, v9 = _parse_ab(str(log), "=== matvec A/B v9: ")
    assert gse == 13.741 and v9 == 3.1

    # a failed variant yields None for v9 and the engage gate must stay
    # closed (tools/hw_v9_ab.maybe_engage_flagship's first branch)
    fake.write_text('print("xla (gse):      13.741 ms/matvec")\n'
                    'print("pallas v9 C=8: FAILED MosaicError: nope")\n')
    hw_session.run_step(str(log), "matvec A/B v9", [str(fake)],
                        timeout=60, gate_s=0)
    gse, v9 = _parse_ab(str(log), "=== matvec A/B v9: ")
    assert gse == 13.741 and v9 is None


def test_run_step_timeout_kills_group(tmp_path, monkeypatch):
    """A hung step must be killed at its timeout and logged as TIMEOUT,
    and the next step must see _last_step_ok False (the wedged-grant
    gate trigger)."""
    from tools import hw_session

    hang = tmp_path / "hang.py"
    hang.write_text("import time\ntime.sleep(60)\n")
    log = tmp_path / "log.txt"
    monkeypatch.setattr(hw_session, "_last_step_ok", True)
    hw_session.run_step(str(log), "hang step", [str(hang)],
                        timeout=2, gate_s=0)
    text = log.read_text()
    assert "TIMEOUT after 2s" in text
    assert hw_session._last_step_ok is False


def test_run_step_ok_rcs_verdict_exits(tmp_path, monkeypatch):
    """Steps whose nonzero exit is a VERDICT (cache_key_check rc=4 =
    determined MISMATCH) must not trip the next step's wedged-grant
    gate."""
    from tools import hw_session

    v = tmp_path / "verdict.py"
    v.write_text("import sys\nsys.exit(4)\n")
    log = tmp_path / "log.txt"
    monkeypatch.setattr(hw_session, "_last_step_ok", True)
    hw_session.run_step(str(log), "verdict step", [str(v)],
                        timeout=30, gate_s=0, ok_rcs=(0, 4))
    assert hw_session._last_step_ok is True
    hw_session.run_step(str(log), "verdict step strict", [str(v)],
                        timeout=30, gate_s=0)
    assert hw_session._last_step_ok is False


def test_priority_queue_step_order_has_pipelined_after_fused(tmp_path,
                                                             monkeypatch):
    """ISSUE 11: the priority preset's variant A/B must run classic ->
    fused -> pipelined as ADJACENT bench steps sharing one warm cache
    dir (three adjacent lines = the 3-way ms/iter A/B), with the lint
    gate still step 0 and Pallas v9 still in the queue.  Recorded by
    monkeypatching run_step — no accelerator, no subprocesses."""
    from tools import hw_session

    steps = []

    def fake_run_step(path, name, argv, env_extra=None, **kw):
        steps.append((name, dict(env_extra or {})))
        return "rc=0"

    monkeypatch.setattr(hw_session, "run_step", fake_run_step)
    hw_session.run_priority_queue(str(tmp_path / "log.txt"), quick=True)

    names = [n for n, _ in steps]
    assert names[0] == "contract lint (step 0)"
    # the overlap lint gates the pipelined leg (the fast lint can't:
    # psum-overlap is fast=False and --fast traces no pipelined
    # programs) — it runs on CPU, before any hardware step
    i_ov = names.index("overlap lint (step 0.2)")
    i_c = names.index("flagship classic")
    i_f = names.index("flagship fused")
    i_p = names.index("flagship pipelined")
    assert 0 < i_ov < i_c < i_f < i_p and i_p == i_f + 1, names
    env = dict(steps)
    assert env["overlap lint (step 0.2)"]["JAX_PLATFORMS"] == "cpu"
    assert env["flagship pipelined"]["BENCH_PCG_VARIANT"] == "pipelined"
    assert env["flagship fused"]["BENCH_PCG_VARIANT"] == "fused"
    assert "BENCH_PCG_VARIANT" not in env["flagship classic"]
    # the three variant legs share ONE warm cache dir (the A/B contract:
    # steps 2-3 reuse step 1's caches) and one pinned size
    for leg in ("flagship classic", "flagship fused", "flagship pipelined"):
        assert env[leg].get("BENCH_CACHE_DIR") == \
            env["flagship classic"]["BENCH_CACHE_DIR"]
        assert env[leg].get("BENCH_NX") == env["flagship classic"]["BENCH_NX"]
    # the rest of the queue survives the insertion
    assert any(n.startswith("mg A/B") for n in names)
    assert "matvec A/B v9" in names


def test_priority_queue_setup_ladder_after_lint_before_variants(
        tmp_path, monkeypatch):
    """ISSUE 14: the setup-ladder leg runs AFTER the lints (a broken
    structural claim aborts first), BEFORE the variant A/Bs, on CPU,
    sharing the warm cache dir, and writes the SETUP_LADDER.json
    artifact."""
    from tools import hw_session

    steps = []

    def fake_run_step(path, name, argv, env_extra=None, **kw):
        steps.append((name, dict(env_extra or {})))
        return "rc=0"

    monkeypatch.setattr(hw_session, "run_step", fake_run_step)
    hw_session.run_priority_queue(str(tmp_path / "log.txt"), quick=True)

    names = [n for n, _ in steps]
    i_lint = names.index("contract lint (step 0)")
    i_ladder = names.index("setup ladder")
    i_c = names.index("flagship classic")
    assert i_lint < i_ladder < i_c, names
    env = dict(steps)["setup ladder"]
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["BENCH_SETUP_LADDER"]
    assert env["BENCH_SETUP_OUT"].endswith("SETUP_LADDER.json")
    # shares the variant legs' warm cache dir (the A/B steps inherit
    # whatever the ladder already warmed)
    assert env["BENCH_CACHE_DIR"] == \
        dict(steps)["flagship classic"]["BENCH_CACHE_DIR"]


def test_priority_queue_serve_smoke_on_cpu_before_variants(
        tmp_path, monkeypatch):
    """ISSUE 19: the serve smoke (3 jobs through a live daemon, one
    ``exc@job:`` fault, 2 done + 1 failed with a named verdict) runs on
    CPU after the distributed-chaos smoke, before any hardware grant is
    spent on the flagship legs."""
    from tools import hw_session

    steps = []

    def fake_run_step(path, name, argv, env_extra=None, **kw):
        steps.append((name, dict(env_extra or {})))
        return "rc=0"

    monkeypatch.setattr(hw_session, "run_step", fake_run_step)
    hw_session.run_priority_queue(str(tmp_path / "log.txt"), quick=True)

    names = [n for n, _ in steps]
    i_chaos = names.index("distributed-chaos smoke")
    i_serve = names.index("serve smoke")
    i_c = names.index("flagship classic")
    assert i_chaos < i_serve < i_c, names
    assert dict(steps)["serve smoke"]["JAX_PLATFORMS"] == "cpu"


def test_priority_queue_aborts_on_lint_failure(tmp_path, monkeypatch):
    """A FAILED step-0 lint must abort before any hardware step — the
    pipelined leg's overlap claim is exactly what the lint proves, so
    measuring after a FAIL would benchmark a disproven claim."""
    from tools import hw_session

    steps = []

    def fake_run_step(path, name, argv, env_extra=None, **kw):
        steps.append(name)
        return "rc=1"

    monkeypatch.setattr(hw_session, "run_step", fake_run_step)
    hw_session.run_priority_queue(str(tmp_path / "log.txt"), quick=True)
    assert steps == ["contract lint (step 0)"]


def test_priority_queue_overlap_lint_failure_skips_pipelined_only(
        tmp_path, monkeypatch):
    """A FAILED step-0.2 overlap lint must skip ONLY the pipelined leg
    (its ms/iter number would measure a disproven latency-hiding claim)
    while the classic/fused/MG/nrhs/Pallas steps — none of which depend
    on the overlap claim — still run and use the window."""
    from tools import hw_session

    steps = []

    def fake_run_step(path, name, argv, env_extra=None, **kw):
        steps.append((name, list(argv)))
        if name == "overlap lint (step 0.2)":
            return "rc=1"
        return "rc=0"

    monkeypatch.setattr(hw_session, "run_step", fake_run_step)
    hw_session.run_priority_queue(str(tmp_path / "log.txt"), quick=True)

    names = [n for n, _ in steps]
    assert "flagship pipelined" not in names
    for kept in ("flagship classic", "flagship fused",
                 "mg A/B anchor (jacobi)", "matvec A/B v9"):
        assert any(n.startswith(kept) for n in names), (kept, names)
    # the step really invokes the psum-overlap rule alone (full tier)
    argv = dict(steps)["overlap lint (step 0.2)"]
    assert "--rules" in argv and "psum-overlap" in argv
    assert "--fast" not in argv
    log = (tmp_path / "log.txt").read_text()
    assert "SKIPPING the flagship pipelined leg" in log


def test_parse_ab_missing_marker_or_file_returns_none(tmp_path):
    """ADVICE r05 #3: a missing marker (step died before its section
    header) or an unreadable log must not raise out of _parse_ab — the
    remaining independent steps of a scarce hardware window continue,
    and maybe_engage_flagship's None gate keeps the engage closed."""
    from tools.hw_v9_ab import _parse_ab, maybe_engage_flagship

    log = tmp_path / "log.txt"
    log.write_text("window opened; step never started\n")
    assert _parse_ab(str(log), "=== matvec A/B v9: ") == (None, None)
    # the anomaly breadcrumb landed in the log instead of an exception
    assert "parse anomaly" in log.read_text()

    missing = tmp_path / "never_written.txt"
    assert _parse_ab(str(missing), "=== whatever: ") == (None, None)

    # downstream: no v9 number => no engaged flagship run (and no crash)
    assert maybe_engage_flagship(str(log), None, None) is False


# ----------------------------------------------------------------------
# flight records around queue steps (ISSUE 12, obs/flight.py): the
# crash-durable twin of the session log.
# ----------------------------------------------------------------------

def test_run_step_writes_flight_brackets_in_order(tmp_path, monkeypatch):
    """Every run_step is bracketed by begin/end flight records in the
    session log's .flight.jsonl twin — ordered, seq-matched, and
    verdict=clean after a clean queue."""
    from pcg_mpi_solver_tpu.obs.flight import (
        flight_verdict_path, read_jsonl_tolerant)
    from tools import hw_session

    ok = tmp_path / "ok.py"
    ok.write_text("print('fine')\n")
    log = tmp_path / "log.txt"
    monkeypatch.setattr(hw_session, "_last_step_ok", True)
    hw_session.run_step(str(log), "first step", [str(ok)],
                        timeout=60, gate_s=0)
    hw_session.run_step(str(log), "second step", [str(ok)],
                        timeout=60, gate_s=0)
    fpath = str(log) + ".flight.jsonl"
    assert os.path.exists(fpath)
    events, truncated = read_jsonl_tolerant(fpath)
    assert truncated == 0
    ops = [(e["op"], e.get("name")) for e in events
           if e["op"] != "heartbeat"]
    assert ops == [("meta", None),
                   ("begin", "step:first step"),
                   ("end", "step:first step"),
                   ("begin", "step:second step"),
                   ("end", "step:second step")]
    # the begin record is written BEFORE the subprocess result exists:
    # it must carry the argv for the post-mortem
    begins = [e for e in events if e["op"] == "begin"]
    assert begins[0]["argv"] == [str(ok)]
    assert flight_verdict_path(fpath)["verdict"] == "clean"


def test_run_step_failure_logs_flight_verdict(tmp_path, monkeypatch):
    """A failed step closes its bracket with op=fail AND prints the
    mechanical post-mortem pointer — flight-record path + verdict —
    into the session log itself."""
    from pcg_mpi_solver_tpu.obs.flight import flight_verdict_path
    from tools import hw_session

    bad = tmp_path / "bad.py"
    bad.write_text("import sys\nsys.exit(3)\n")
    log = tmp_path / "log.txt"
    monkeypatch.setattr(hw_session, "_last_step_ok", True)
    hw_session.run_step(str(log), "doomed step", [str(bad)],
                        timeout=60, gate_s=0)
    assert hw_session._last_step_ok is False
    fpath = str(log) + ".flight.jsonl"
    v = flight_verdict_path(fpath)
    assert v["verdict"] == "failed"
    assert any("doomed step" in f for f in v["fails"])
    text = log.read_text()
    assert f"flight record: {fpath} verdict=failed" in text
    # ...and an ok_rcs-listed verdict exit stays a CLEAN bracket (the
    # cache_key_check rc=4 MISMATCH is an answer, not a failure)
    log2 = tmp_path / "log2.txt"
    hw_session.run_step(str(log2), "verdict step", [str(bad)],
                        timeout=60, gate_s=0, ok_rcs=(0, 3))
    v2 = flight_verdict_path(str(log2) + ".flight.jsonl")
    assert v2["verdict"] == "clean", v2
    assert "flight record:" not in log2.read_text()


def test_stale_flight_artifact_rotated_not_inherited(tmp_path,
                                                     monkeypatch):
    """A leftover flight file from a DEAD previous session on the same
    log path is ingested (verdict logged) and rotated to .prev before
    this session records — otherwise this session's reused seq numbers
    would close the dead session's brackets (its death reads clean) and
    its stale unclosed brackets would poison this session's verdict."""
    from pcg_mpi_solver_tpu.obs.flight import (
        FlightRecorder, flight_verdict_path)
    from tools import hw_session

    log = tmp_path / "log.txt"
    fpath = str(log) + ".flight.jsonl"
    dead = FlightRecorder(fpath, heartbeat_s=30)
    dead.begin("step:killed by tunnel death")     # never closed
    dead.close()
    assert flight_verdict_path(fpath)["verdict"] == "died"

    ok = tmp_path / "ok.py"
    ok.write_text("print('fine')\n")
    monkeypatch.setattr(hw_session, "_last_step_ok", True)
    hw_session.run_step(str(log), "fresh step", [str(ok)],
                        timeout=60, gate_s=0)
    # the dead artifact moved aside intact; the fresh stream is clean
    prev = flight_verdict_path(fpath + ".prev")
    assert prev["verdict"] == "died"
    assert prev["in_flight"] == ["step:killed by tunnel death"]
    v = flight_verdict_path(fpath)
    assert v["verdict"] == "clean", v
    text = log.read_text()
    assert "verdict=died" in text
    assert "in flight at death: step:killed by tunnel death" in text


def test_run_step_survives_flight_recorder_trouble(tmp_path, monkeypatch):
    """Recorder trouble must never cost a hardware window a step:
    run_step logs the problem and runs the subprocess anyway."""
    from tools import hw_session

    def boom(path):
        raise OSError("read-only scratch")

    monkeypatch.setattr(hw_session, "_flight", boom)
    monkeypatch.setattr(hw_session, "_last_step_ok", True)
    ok = tmp_path / "ok.py"
    ok.write_text("print('fine')\n")
    log = tmp_path / "log.txt"
    hw_session.run_step(str(log), "unflighted step", [str(ok)],
                        timeout=60, gate_s=0)
    text = log.read_text()
    assert "flight recorder unavailable" in text
    assert "=== unflighted step done: rc=0" in text
    assert hw_session._last_step_ok is True


# ----------------------------------------------------------------------
# profiled flagship rung (ISSUE 15): ordering + verdict logging +
# failure tolerance
# ----------------------------------------------------------------------

def test_priority_queue_profiled_rung_after_variant_abs(tmp_path,
                                                        monkeypatch):
    """The BENCH_PROFILE=1 rung runs directly AFTER the variant A/Bs
    (classic -> fused -> pipelined) and BEFORE the MG A/B, on the same
    warm cache dir and size, profiling the pipelined variant when the
    overlap lint passed; the overlap + trend verdicts land in the
    session log right after the step."""
    from tools import hw_session

    steps = []

    def fake_run_step(path, name, argv, env_extra=None, **kw):
        steps.append((name, dict(env_extra or {})))
        return "rc=0"

    monkeypatch.setattr(hw_session, "run_step", fake_run_step)
    hw_session.run_priority_queue(str(tmp_path / "log.txt"), quick=True)

    names = [n for n, _ in steps]
    i_p = names.index("flagship pipelined")
    i_prof = names.index("profiled flagship")
    i_mg = names.index("mg A/B anchor (jacobi)")
    assert i_p < i_prof < i_mg, names
    env = dict(steps)["profiled flagship"]
    assert env["BENCH_PROFILE"] == "1"
    assert env["BENCH_PROFILE_DIR"]
    assert env["BENCH_PCG_VARIANT"] == "pipelined"
    assert env["BENCH_CACHE_DIR"] == \
        dict(steps)["flagship classic"]["BENCH_CACHE_DIR"]
    assert env["BENCH_NX"] == dict(steps)["flagship classic"]["BENCH_NX"]
    log = (tmp_path / "log.txt").read_text()
    # no artifact exists under the fake run_step: the verdicts still
    # logged (degraded overlap parse; the trend sentinel ran for real
    # over the committed BENCH_r*.json series)
    assert "overlap verdict" in log
    assert "trend verdict" in log


def test_priority_queue_profiled_rung_classic_when_overlap_fails(
        tmp_path, monkeypatch):
    """A FAILED overlap lint demotes the profiled rung to classic (a
    disproven latency-hiding claim must not be the profiled variant)
    but the rung itself still runs — the attribution table does not
    depend on the overlap claim."""
    from tools import hw_session

    steps = []

    def fake_run_step(path, name, argv, env_extra=None, **kw):
        steps.append((name, dict(env_extra or {})))
        if name == "overlap lint (step 0.2)":
            return "rc=1"
        return "rc=0"

    monkeypatch.setattr(hw_session, "run_step", fake_run_step)
    hw_session.run_priority_queue(str(tmp_path / "log.txt"), quick=True)
    env = dict(steps)["profiled flagship"]
    assert "BENCH_PCG_VARIANT" not in env       # classic default
    assert env["BENCH_PROFILE"] == "1"


def test_log_profile_verdicts_survives_broken_parse(tmp_path,
                                                    monkeypatch):
    """A broken trace parse (or a broken trend read) must not cost the
    step: log_profile_verdicts logs a named reason and returns."""
    from pcg_mpi_solver_tpu.obs import profview, trend
    from tools import hw_session

    def boom(*a, **k):
        raise ValueError("corrupt trace")

    monkeypatch.setattr(profview, "profile_report", boom)
    monkeypatch.setattr(trend, "trend_report", boom)
    log = tmp_path / "log.txt"
    prof = tmp_path / "prof"
    prof.mkdir()
    (prof / "x.trace.json").write_text("{}")    # artifact exists, parse dies
    hw_session.log_profile_verdicts(str(log), str(prof))
    text = log.read_text()
    assert "overlap verdict unavailable (ValueError: corrupt trace)" \
        in text
    assert "trend verdict unavailable" in text
    # ...and a STALE artifact (predating the step) is refused by name:
    # bench swallows capture failures, so an earlier round's trace must
    # not be logged as this round's measured verdict
    log2 = tmp_path / "log2.txt"
    hw_session.log_profile_verdicts(
        str(log2), str(prof),
        since=os.path.getmtime(str(prof / "x.trace.json")) + 60)
    assert "predates this step" in log2.read_text()


def test_log_profile_verdicts_reports_real_artifact(tmp_path,
                                                    monkeypatch):
    """With a real (synthetic) trace artifact on disk the overlap
    verdict line carries the parsed fraction, and a seeded fresh
    regression makes the trend line say REGRESSED."""
    import gzip
    import json as _json

    from tools import hw_session

    prof = tmp_path / "prof"
    prof.mkdir()
    evs = [{"ph": "X", "name": "all-reduce.0", "ts": 0, "dur": 10,
            "pid": 1, "tid": 1, "args": {"hlo_op": "all-reduce.0"}},
           {"ph": "X", "name": "dot.1", "ts": 0, "dur": 10, "pid": 1,
            "tid": 2, "args": {"hlo_op": "dot.1"}}]
    with gzip.open(str(prof / "x.trace.json.gz"), "wb") as f:
        f.write(_json.dumps({"traceEvents": evs}).encode())
    log = tmp_path / "log.txt"
    hw_session.log_profile_verdicts(str(log), str(prof))
    text = log.read_text()
    assert "overlap verdict: 1.000" in text
    assert "trend verdict:" in text
