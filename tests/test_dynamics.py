"""Explicit central-difference dynamics: parity with an independent numpy
integrator, energy sanity, partition-count independence, and the crack-tip
post-processing chain on dynamic frames (reference's vestigial dynamics era
made live: DiagM/Vd/Cm/Me/dt, partition_mesh.py:324-330, 172-175)."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu import RunConfig, SolverConfig
from pcg_mpi_solver_tpu.models import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.dynamics import DynamicsSolver, stable_dt
from pcg_mpi_solver_tpu.solver.numpy_ref import NumpyRefSolver


def numpy_central_difference(model, dt, n_steps, damping=0.0, delta=1.0):
    """Independent host-side integrator (same scheme, plain numpy)."""
    ref = NumpyRefSolver(model)
    n = model.n_dof
    eff = np.zeros(n, dtype=bool)
    eff[model.dof_eff] = True
    inv_m = np.where(model.diag_M > 0, 1.0 / model.diag_M, 0.0)
    u = np.zeros(n)
    v = np.zeros(n)
    out = []
    for s in range(n_steps):
        a = inv_m * (model.F * delta - ref.matvec(u)) - damping * v
        v = v + dt * a
        u = u + dt * v
        u[~eff] = model.Ud[~eff] * delta
        v[~eff] = model.Vd[~eff] * delta
        out.append(u.copy())
    return np.stack(out)


@pytest.fixture(scope="module")
def model():
    return make_cube_model(4, 3, 3, E=100.0, nu=0.25, rho=1.0,
                           load="traction", load_value=1.0,
                           heterogeneous=True)


def test_matches_numpy_integrator(model):
    dt = stable_dt(model, safety=0.5)
    n_steps = 25
    ref_traj = numpy_central_difference(model, dt, n_steps, damping=0.05)

    dyn = DynamicsSolver(model, RunConfig(), mesh=make_mesh(4), n_parts=4,
                         dt=dt, damping=0.05,
                         probe_dofs=(6, 13))
    res = dyn.run(n_steps, export_every=5)
    np.testing.assert_allclose(res.u, ref_traj[-1], rtol=1e-9, atol=1e-12)
    # probe history matches the reference trajectory at those dofs
    np.testing.assert_allclose(res.probe_u[0], ref_traj[:, 6],
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(res.probe_u[1], ref_traj[:, 13],
                               rtol=1e-9, atol=1e-12)
    # frames every 5 steps
    assert len(res.frames) == 5
    np.testing.assert_allclose(res.frames[1], ref_traj[9],
                               rtol=1e-9, atol=1e-12)


def test_partition_independence(model):
    dt = stable_dt(model, safety=0.5)
    r1 = DynamicsSolver(model, RunConfig(), mesh=make_mesh(1), n_parts=1,
                        dt=dt).run(20)
    r8 = DynamicsSolver(model, RunConfig(), mesh=make_mesh(8), n_parts=8,
                        dt=dt).run(20)
    np.testing.assert_allclose(r8.u, r1.u, rtol=1e-10, atol=1e-13)


def test_stability_and_damping(model):
    """Undamped: bounded oscillation.  Damped: decays toward the static
    solution (long-time limit of mass-damped dynamics)."""
    dt = stable_dt(model, safety=0.5)
    dyn = DynamicsSolver(model, RunConfig(), mesh=make_mesh(4), n_parts=4,
                         dt=dt, damping=2.0)
    res = dyn.run(4000)
    from pcg_mpi_solver_tpu.solver.numpy_ref import NumpyRefSolver

    stat = NumpyRefSolver(model).solve(tol=1e-10)
    np.testing.assert_allclose(res.u, stat.u, rtol=0,
                               atol=5e-3 * np.abs(stat.u).max())


def test_crack_tip_chain(model):
    """Dynamic frames feed the crack-tip post-processing utilities."""
    from pcg_mpi_solver_tpu.utils.postproc import smooth_moving_average

    dt = stable_dt(model, safety=0.5)
    dyn = DynamicsSolver(model, RunConfig(), mesh=make_mesh(4), n_parts=4,
                         dt=dt, probe_dofs=(3,))
    res = dyn.run(50)
    sm = smooth_moving_average(res.probe_u[0], half_window=5)
    assert sm.shape == res.probe_u[0].shape
    assert np.isfinite(sm).all()


def test_dynamics_hybrid_matches_general():
    """Octree dynamics on the hybrid level-grid backend: identical
    trajectory to the general gather/scatter path."""
    from pcg_mpi_solver_tpu.models.octree import make_octree_model
    from pcg_mpi_solver_tpu.solver.dynamics import DynamicsSolver, stable_dt

    model = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3,
                              load="traction", load_value=1.0)
    dt = 0.5 * stable_dt(model)
    out = {}
    for b in ("general", "hybrid"):
        dyn = DynamicsSolver(model, RunConfig(), mesh=make_mesh(4),
                             n_parts=4, dt=dt, damping=0.1, backend=b)
        assert dyn.backend == b
        res = dyn.run(50)
        out[b] = np.asarray(res.u)
    scale = max(np.abs(out["general"]).max(), 1e-30)
    np.testing.assert_allclose(out["hybrid"], out["general"],
                               rtol=0, atol=1e-11 * scale)


def test_dynamics_pallas_interpret_routes_interpreter():
    """pallas='interpret' must reach the HybridOps built by
    select_time_backend with pallas_interpret=True — otherwise a CPU CI
    run would attempt a real Mosaic lowering on the first step (the
    regression this guards: the quasi-static driver was updated but the
    dynamics backend factory was not)."""
    from pcg_mpi_solver_tpu.models.octree import make_octree_model

    model = make_octree_model(3, 3, 3, max_level=2, n_incl=2, seed=5,
                              load="traction", load_value=1e6)
    cfg = RunConfig(solver=SolverConfig(dtype="float32",
                                        pallas="interpret"))
    s = DynamicsSolver(model, cfg, mesh=make_mesh(1), n_parts=1,
                       backend="hybrid")
    assert s.ops.use_pallas and s.ops.pallas_interpret
    assert any(s.ops.pallas_levels)
    r = s.run(2)                    # two explicit steps through the kernel
    assert np.all(np.isfinite(np.asarray(r.u)))
