"""Resilience subsystem: deterministic fault injection drives every
recovery path end to end on CPU — breakdown ladder (flag 2/4, NaN
carry), dispatch guard (device-loss redispatch from the mid-Krylov
snapshot), f64 escalation in mixed mode, and the snapshot store's
fingerprint/corruption guards.  Kill-and-resume parity lives in
tests/test_checkpoint.py (it is a checkpoint-contract test); the
engineered flag-2/flag-4 ladder recoveries also run in tests/test_pcg.py
(they are a PCG-flag-contract test)."""

import os

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.resilience import (
    DispatchGuard, FaultPlan, InjectedDispatchError, RecoveryLadder,
    SimulatedKill, breakdown_trigger, is_device_loss)
from pcg_mpi_solver_tpu.solver.driver import Solver


class _Capture:
    """Metrics sink collecting events for assertions."""

    def __init__(self):
        self.events = []

    def emit(self, ev):
        self.events.append(ev)

    def close(self):
        pass


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("PCG_TPU_RETRY_BACKOFF_S", "0.01")


@pytest.fixture(scope="module")
def model():
    return make_cube_model(5, 4, 4, heterogeneous=True)


def _solver(model, tmp_path=None, fault=None, recorder=None, n_dev=1,
            snapshot_every=0, **solver_kw):
    solver_kw.setdefault("tol", 1e-8)
    solver_kw.setdefault("max_iter", 2000)
    solver_kw.setdefault("iters_per_dispatch", 12)
    cfg = RunConfig(
        scratch_path=str(tmp_path) if tmp_path is not None else "./scratch",
        solver=SolverConfig(**solver_kw),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    cfg.snapshot_every = snapshot_every
    s = Solver(model, cfg, mesh=make_mesh(n_dev), n_parts=n_dev,
               recorder=recorder)
    if fault is not None:
        s.fault_plan = FaultPlan(fault, recorder=s.recorder)
    return s


def _recoveries(cap):
    return [(e["action"], e["trigger"]) for e in cap.events
            if e["kind"] == "recovery"]


# ----------------------------------------------------------------------
# Fault-plan plumbing
# ----------------------------------------------------------------------

def test_faultplan_parse_and_counters():
    p = FaultPlan("exc@2*2, kill@5, rho0@1")
    assert p.armed
    # dispatch counter: exc fires before dispatch 2, twice (retry too)
    p.dispatches = 2
    with pytest.raises(InjectedDispatchError):
        p.on_dispatch()
    with pytest.raises(InjectedDispatchError):
        p.on_dispatch()
    p.on_dispatch()                      # third attempt proceeds
    assert [f["mode"] for f in p.fired] == ["exc", "exc"]

    with pytest.raises(ValueError, match="unknown fault mode"):
        FaultPlan("frobnicate@1")
    with pytest.raises(ValueError, match="bad fault term"):
        FaultPlan("exc@")
    assert not FaultPlan("").armed
    assert FaultPlan.from_env() is None  # env unset


def test_faultplan_boundary_poison_and_kill():
    import jax.numpy as jnp

    p = FaultPlan("inf@0, rho0@1, kill@2")
    carry = {"r": jnp.asarray([0.0, 2.0, -1.0]),
             "rho": jnp.asarray(3.0)}
    c0 = p.at_boundary(dict(carry))
    r0 = np.asarray(c0["r"])
    assert np.isinf(r0[1]) and np.isinf(r0[2]) and r0[0] == 0.0
    c1 = p.at_boundary(dict(carry))
    assert float(c1["rho"]) == 0.0
    with pytest.raises(SimulatedKill):
        p.at_boundary(dict(carry))
    # the original carry leaves were never mutated in place
    assert float(carry["rho"]) == 3.0
    assert np.all(np.isfinite(np.asarray(carry["r"])))

    # a poison whose target leaf is absent (rho0 on the mixed outer
    # state) must neither fire nor consume its count: a chaos drill must
    # not read "exercised" off an injection that could not land
    p2 = FaultPlan("rho0@0")
    out = p2.at_boundary({"r": carry["r"]})
    assert p2.fired == [] and p2.armed
    assert np.all(np.isfinite(np.asarray(out["r"])))


def test_faultplan_column_domain():
    """Column-domain faults (``mode@col:k``, ISSUE 9): fire only at
    BLOCKED boundaries, poison only column k (other columns bitwise
    untouched — the fault-isolation tests depend on it), and consume
    their counts; kill/exc have no column form."""
    import jax.numpy as jnp

    blocked = {"r": jnp.asarray([[[1.0, 2.0, 3.0],
                                  [0.0, 4.0, -1.0]]]),
               "rho": jnp.asarray([1.0, 2.0, 3.0])}
    p = FaultPlan("nan@col:1, rho0@col:2")
    assert p.armed and p.col_armed
    # non-blocked boundaries never fire column faults
    out = p.at_boundary(dict(blocked))
    assert p.fired == [] and p.col_armed
    out = p.at_boundary(dict(blocked), blocked=True)
    r = np.asarray(out["r"])
    assert np.isnan(r[..., 1]).all()
    np.testing.assert_array_equal(r[..., 0],
                                  np.asarray(blocked["r"])[..., 0])
    np.testing.assert_array_equal(r[..., 2],
                                  np.asarray(blocked["r"])[..., 2])
    rho = np.asarray(out["rho"])
    assert rho[2] == 0.0 and rho[0] == 1.0 and rho[1] == 2.0
    assert sorted((f["mode"], f["point"], f["at"]) for f in p.fired) == \
        [("nan", "col", 1), ("rho0", "col", 2)]
    assert not p.col_armed      # counts consumed: later boundaries clean
    out2 = p.at_boundary(dict(blocked), blocked=True)
    assert np.all(np.isfinite(np.asarray(out2["r"])))

    # inf lands only on the column's NONZERO entries (constrained dofs
    # stay exactly 0, same contract as the whole-carry poisoner)
    p3 = FaultPlan("inf@col:2")
    out3 = p3.at_boundary(dict(blocked), blocked=True)
    r3 = np.asarray(out3["r"])
    assert np.isinf(r3[0, 1, 2]) and r3[0, 0, 2] == np.inf
    np.testing.assert_array_equal(r3[..., 0],
                                  np.asarray(blocked["r"])[..., 0])

    # ``*count`` re-fires on consecutive blocked boundaries
    p4 = FaultPlan("nan@col:0*2")
    p4.at_boundary(dict(blocked), blocked=True)
    p4.at_boundary(dict(blocked), blocked=True)
    assert len(p4.fired) == 2 and not p4.col_armed

    # an out-of-range column cannot land: neither consumed nor fired
    # (same contract as the absent-leaf case)
    p5 = FaultPlan("nan@col:7")
    out5 = p5.at_boundary(dict(blocked), blocked=True)
    assert p5.fired == [] and p5.col_armed
    assert np.all(np.isfinite(np.asarray(out5["r"])))

    with pytest.raises(ValueError, match="column-domain"):
        FaultPlan("kill@col:1")
    with pytest.raises(ValueError, match="column-domain"):
        FaultPlan("exc@col:0")


def test_column_trigger_classification():
    """Per-column ladder triggers (resilience/recovery.column_trigger):
    flags 2/4 and the fused drift flag 6 are breakdown triggers, a
    still-running column with a non-finite carry norm is nan_carry, and
    converged/budget/stagnation/quarantined columns trigger nothing."""
    from pcg_mpi_solver_tpu.resilience import column_trigger

    assert column_trigger(2, 1.0) == "flag2"
    assert column_trigger(4, 1.0) == "flag4"
    assert column_trigger(6, 1.0) == "flag6"
    assert column_trigger(1, float("nan")) == "nan_carry"
    assert column_trigger(1, float("inf")) == "nan_carry"
    assert column_trigger(1, 0.5) is None
    assert column_trigger(0, 1.0) is None
    assert column_trigger(3, 1.0) is None
    assert column_trigger(5, float("nan")) is None    # already terminal


def test_device_loss_classification():
    assert is_device_loss(InjectedDispatchError("x"))
    assert is_device_loss(RuntimeError("rpc failed: UNAVAILABLE: socket"))
    assert not is_device_loss(ValueError("shapes mismatch"))

    class XlaRuntimeError(Exception):
        pass

    assert is_device_loss(XlaRuntimeError("boom"))


def test_breakdown_trigger_taxonomy():
    assert breakdown_trigger(2, 0.5) == "flag2"
    assert breakdown_trigger(4, 0.5) == "flag4"
    assert breakdown_trigger(1, float("nan")) == "nan_carry"
    assert breakdown_trigger(0, float("inf")) == "nan_carry"
    assert breakdown_trigger(0, 1e-9) is None
    assert breakdown_trigger(1, 0.5) is None     # budget: not recoverable
    assert breakdown_trigger(3, 0.5) is None     # stagnation: not either


def test_ladder_rung_order_and_budget():
    lad = RecoveryLadder(precond="block3", mixed=True, max_recoveries=5)
    acts = [lad.next_action("flag4") for _ in range(6)]
    assert acts == ["restart_minres", "fallback_prec", "escalate_f64",
                    "escalate_f64", "escalate_f64", None]
    # scalar jacobi has no weaker fallback; direct mode no escalation
    lad2 = RecoveryLadder(precond="jacobi", mixed=False, max_recoveries=2)
    assert [lad2.next_action("flag2") for _ in range(3)] == \
        ["restart_minres", "restart_minres", None]


def test_dispatch_guard_budget():
    g = DispatchGuard(retries=2)
    e = InjectedDispatchError("x")
    assert g.should_retry(e) and g.should_retry(e)
    assert not g.should_retry(e)                 # budget spent
    assert not DispatchGuard(retries=5).should_retry(ValueError("no"))
    # deadline clamp (PCG_TPU_RETRY_DEADLINE_S via the driver): a past
    # deadline refuses retries even with budget left
    assert not DispatchGuard(retries=5, deadline_s=-1.0).should_retry(e)
    assert DispatchGuard(retries=5, deadline_s=3600.0).should_retry(e)


# ----------------------------------------------------------------------
# End-to-end recovery on the chunked solve path (CPU, tier-1)
# ----------------------------------------------------------------------

def test_nan_carry_recovers(model):
    """NaN poison trips NO in-graph flag (pcg.py BREAKDOWN_FLAGS) — the
    host-side detection must break within one chunk and the ladder must
    recover from the min-residual iterate to full convergence."""
    cap = _Capture()
    s = _solver(model, fault="nan@1",
                recorder=MetricsRecorder(sinks=[cap]))
    res = s.step(1.0)
    assert res.flag == 0 and res.relres <= 1e-8
    assert ("restart_minres", "nan_carry") in _recoveries(cap)


def test_dispatch_exception_without_snapshot_restarts_step(model):
    """Device loss with no snapshot to re-dispatch from: the guard has
    nothing safe to restore (the donated carry may be gone with the
    failed dispatch), so the ladder restarts the step from its start
    state — visible as a device_loss recovery event."""
    cap = _Capture()
    s = _solver(model, fault="exc@2",
                recorder=MetricsRecorder(sinks=[cap]))
    res = s.step(1.0)
    assert res.flag == 0 and res.relres <= 1e-8
    assert ("restart_minres", "device_loss") in _recoveries(cap)


def test_dispatch_exception_redispatches_from_snapshot(model, tmp_path):
    """With mid-Krylov snapshots on, a device-loss exception re-dispatches
    from the last snapshot via the guard — same final answer, and the
    recovery event says redispatch, not a from-scratch restart."""
    cap = _Capture()
    s = _solver(model, tmp_path, fault="exc@3", snapshot_every=1,
                recorder=MetricsRecorder(sinks=[cap]))
    ref = _solver(model)
    r_ref = ref.step(1.0)
    res = s.step(1.0)
    assert res.flag == 0
    recs = _recoveries(cap)
    assert ("redispatch", "device_loss") in recs
    assert ("restart_minres", "device_loss") not in recs
    # re-dispatching from the chunk-boundary snapshot replays the lost
    # chunk exactly: iteration count and history match the clean solve
    assert res.iters == r_ref.iters
    assert res.relres == r_ref.relres
    np.testing.assert_array_equal(s.displacement_global(),
                                  ref.displacement_global())


def test_recovery_budget_exhausts_to_honest_failure(model):
    """More faults than budget: the solve reports the real flag instead
    of looping — and the attempts are all on record."""
    cap = _Capture()
    s = _solver(model, fault="rho0@1,rho0@2,rho0@3,rho0@4,rho0@5,rho0@6",
                max_recoveries=2, recorder=MetricsRecorder(sinks=[cap]))
    res = s.step(1.0)
    assert res.flag == 4
    assert len(_recoveries(cap)) == 2


def test_max_recoveries_zero_is_report_and_stop(model):
    """The historical behavior is one knob away: no ladder, the
    breakdown flag comes back to the caller untouched."""
    cap = _Capture()
    s = _solver(model, fault="rho0@1", max_recoveries=0,
                recorder=MetricsRecorder(sinks=[cap]))
    res = s.step(1.0)
    assert res.flag == 4
    assert _recoveries(cap) == []


def test_block3_ladder_reaches_fallback_prec(model):
    """Ladder rung 2 end to end: with the block-Jacobi preconditioner, a
    second breakdown retries under the scalar-Jacobi fallback inverse
    (ops/precond.fallback_kind) — a differently-shaped prec dispatched
    through the same jitted engine — and converges."""
    cap = _Capture()
    s = _solver(model, fault="rho0@1,rho0@2", precond="block3",
                recorder=MetricsRecorder(sinks=[cap]))
    res = s.step(1.0)
    assert res.flag == 0 and res.relres <= 1e-8
    assert _recoveries(cap) == [("restart_minres", "flag4"),
                                ("fallback_prec", "flag4")]


def test_mixed_mode_ladder_escalates_to_f64(model):
    """Mixed mode: a repeatedly-corrupted residual escalates past the
    plain restart to direct-f64 cycles (ladder rung 3) and still
    converges to the outer tolerance.  (An Inf residual in mixed mode is
    caught by the engine's corrupted-residual check as nan_carry — the
    inner pcg would otherwise mistake an Inf rhs for instant
    convergence via tolb = tol * ||Inf|| = Inf and stall to flag 3.)"""
    cap = _Capture()
    s = _solver(model, fault="inf@0,inf@1", precision_mode="mixed",
                dtype="float32", dot_dtype="float64", tol=1e-9,
                max_iter=4000, inner_tol=0.1, max_recoveries=3,
                recorder=MetricsRecorder(sinks=[cap]))
    res = s.step(1.0)
    assert res.flag == 0 and res.relres <= 1e-9
    recs = _recoveries(cap)
    assert recs[0] == ("restart_minres", "nan_carry")
    assert ("escalate_f64", "nan_carry") in recs


def test_healthy_solve_is_untouched(model):
    """With the subsystem at defaults (ladder armed, no faults, no
    snapshots), a healthy chunked solve runs the exact same dispatch
    sequence and produces bit-identical results to max_recoveries=0."""
    r_on = _solver(model).step(1.0)
    r_off = _solver(model, max_recoveries=0).step(1.0)
    assert r_on.flag == r_off.flag == 0
    assert r_on.iters == r_off.iters
    assert r_on.relres == r_off.relres


def test_mixed_kill_resume_and_guard_redispatch(model, tmp_path):
    """The mixed-path restore (outer refinement state at cycle
    boundaries) round-trips both ways it is consumed: a kill-and-resume
    reproduces the uninterrupted solve bit-identically, and a guarded
    device-loss re-dispatch converges to the same answer."""
    def mcfg(run_id):
        cfg = RunConfig(
            scratch_path=str(tmp_path), run_id=run_id, checkpoint_every=1,
            solver=SolverConfig(tol=1e-9, max_iter=4000,
                                iters_per_dispatch=12,
                                precision_mode="mixed", dtype="float32",
                                dot_dtype="float64", inner_tol=0.1),
            time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                           export_flag=False))
        cfg.snapshot_every = 1
        return cfg

    sa = Solver(model, mcfg("mc"), mesh=make_mesh(4), n_parts=4)
    sa.solve()
    cb = mcfg("mk")
    sk = Solver(model, cb, mesh=make_mesh(4), n_parts=4)
    sk.fault_plan = FaultPlan("kill@2")
    with pytest.raises(SimulatedKill):
        sk.solve()
    sk2 = Solver(model, cb, mesh=make_mesh(4), n_parts=4)
    sk2.solve(resume=True)
    assert sk2.flags == sa.flags and sk2.iters == sa.iters
    assert sk2.relres == sa.relres
    np.testing.assert_array_equal(sk2.displacement_global(),
                                  sa.displacement_global())

    sg = Solver(model, mcfg("mg"), mesh=make_mesh(4), n_parts=4)
    sg.fault_plan = FaultPlan("exc@3")
    rg = sg.solve()[0]
    assert rg.flag == 0 and rg.relres <= 1e-9


# ----------------------------------------------------------------------
# Snapshot store contracts
# ----------------------------------------------------------------------

def test_snapshot_store_roundtrip_and_guards(tmp_path):
    from pcg_mpi_solver_tpu.utils.checkpoint import SnapshotStore

    fp = {"model_hash": "abc", "tol": 1e-8}
    store = SnapshotStore(str(tmp_path), fp)
    state = {"kind": "direct", "chunk": 3, "total": 36,
             "carry": {"x": np.arange(6.0).reshape(1, 6),
                       "rho": np.float64(2.5),
                       "trace": {"normr": np.ones(4, np.float32)}}}
    store.save(1, state)
    got = SnapshotStore(str(tmp_path), fp).load(1)
    assert str(np.asarray(got["kind"])) == "direct"
    assert int(got["total"]) == 36
    np.testing.assert_array_equal(got["carry"]["x"], state["carry"]["x"])
    np.testing.assert_array_equal(got["carry"]["trace"]["normr"],
                                  state["carry"]["trace"]["normr"])

    # fingerprint drift is refused loudly
    with pytest.raises(ValueError, match="mismatch"):
        SnapshotStore(str(tmp_path), {"model_hash": "abc",
                                      "tol": 1e-4}).load(1)

    # a truncated snapshot reads as absent (the step restarts cold)
    f = os.path.join(str(tmp_path), "snap_000001.npz")
    blob = open(f, "rb").read()
    with open(f, "wb") as fh:
        fh.write(blob[: len(blob) // 2])
    with pytest.warns(UserWarning, match="unreadable"):
        assert SnapshotStore(str(tmp_path), fp).load(1) is None

    # absent / discarded
    assert store.load(7) is None
    store.save(2, state)
    store.discard(2)
    assert store.load(2) is None


def test_snapshot_resume_requires_explicit_resume(model, tmp_path):
    """A FRESH solve never consumes a stale snapshot: without
    resume=True the persisted mid-step state is ignored (then discarded
    when the step completes)."""
    cap = _Capture()
    s = _solver(model, tmp_path, snapshot_every=1,
                recorder=MetricsRecorder(sinks=[cap]))
    cfg = s.config
    res = s.solve()
    assert all(r.flag == 0 for r in res)
    saves = [e for e in cap.events if e["kind"] == "snapshot"
             and e["op"] == "save"]
    assert saves, "expected mid-Krylov snapshots to be written"
    assert not [e for e in cap.events if e["kind"] == "snapshot"
                and e["op"] == "restore"]
    # completed steps discarded their snapshots
    leftover = ([f for f in os.listdir(cfg.checkpoint_path)
                 if f.startswith("snap_")]
                if os.path.isdir(cfg.checkpoint_path) else [])
    assert leftover == []
