"""Native (C++) runtime library: partitioner + prep kernels.

The reference's native surface is external (METIS via mgmetis,
run_metis.py:84-88; wished-for Cython loops, partition_mesh.py:244).  Ours is
first-party (native/src/*.cpp via ctypes) — these tests cover build, parity
with the numpy fallbacks, and the end-to-end solve on a graph partition.
"""

import numpy as np
import pytest

from pcg_mpi_solver_tpu import native
from pcg_mpi_solver_tpu.models import make_cube_model
from pcg_mpi_solver_tpu.parallel.partition import (
    graph_partition, make_elem_part, rcb_partition)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not buildable")


@pytest.fixture(scope="module")
def cube():
    return make_cube_model(12, 8, 8)


def _dual(cube, ncommon):
    eptr = np.asarray(cube.elem_nodes_offset, dtype=np.int64)
    eind = np.asarray(cube.elem_nodes_flat, dtype=np.int64)
    return native.build_dual_graph_np(eptr, eind, cube.n_node, ncommon=ncommon)


def test_part_mesh_dual_balance_and_coverage(cube):
    part = graph_partition(cube, 8)
    assert part.shape == (cube.n_elem,)
    counts = np.bincount(part, minlength=8)
    assert counts.min() > 0
    # balance within 10% of ideal
    ideal = cube.n_elem / 8
    assert counts.max() <= 1.10 * ideal
    assert counts.min() >= 0.90 * ideal


def test_part_mesh_dual_deterministic(cube):
    p1 = graph_partition(cube, 4, seed=7)
    p2 = graph_partition(cube, 4, seed=7)
    np.testing.assert_array_equal(p1, p2)


def test_edge_cut_reasonable(cube):
    """Graph partition's cut should be in the same ballpark as RCB (which is
    near-optimal on a uniform structured brick)."""
    xadj, adjncy = _dual(cube, ncommon=4)
    cut_g = native.edge_cut(xadj, adjncy, graph_partition(cube, 8))
    cut_r = native.edge_cut(xadj, adjncy, rcb_partition(cube.sctrs, 8).astype(np.int32))
    assert cut_g <= 2.0 * cut_r


def test_edge_cut_matches_numpy(cube):
    xadj, adjncy = _dual(cube, ncommon=4)
    part = rcb_partition(cube.sctrs, 4).astype(np.int32)
    native_cut = native.edge_cut(xadj, adjncy, part)
    src = np.repeat(np.arange(len(xadj) - 1), np.diff(xadj))
    np_cut = int((part[src] != part[adjncy]).sum() // 2)
    assert native_cut == np_cut


def test_csr_take_parity(cube):
    flat = np.asarray(cube.elem_dofs_flat, dtype=np.int64)
    offset = np.asarray(cube.elem_dofs_offset, dtype=np.int64)
    rng = np.random.default_rng(0)
    elems = rng.choice(cube.n_elem, size=5000, replace=True).astype(np.int64)
    out = native.csr_take(flat, offset, elems)
    assert out is not None
    ref = np.concatenate([flat[offset[e]:offset[e + 1]] for e in elems])
    np.testing.assert_array_equal(out, ref)


def test_unique_renumber_parity():
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 3000, size=20000)
    out = native.unique_renumber(ids)
    assert out is not None
    uniq, loc = out
    np.testing.assert_array_equal(uniq, np.unique(ids))
    np.testing.assert_array_equal(uniq[loc], ids)


def test_sort_i32_parity():
    rng = np.random.default_rng(2)
    keys = rng.integers(0, 500, size=10000).astype(np.int32)
    out = native.sort_i32(keys)
    assert out is not None
    perm, skeys = out
    np.testing.assert_array_equal(perm, np.argsort(keys, kind="stable"))
    np.testing.assert_array_equal(skeys, keys[perm])


def test_make_elem_part_methods(cube):
    for method in ("rcb", "graph", "auto"):
        part = make_elem_part(cube, 4, method=method)
        assert len(np.unique(part)) == 4
    with pytest.raises(ValueError):
        make_elem_part(cube, 4, method="bogus")


def test_solve_on_graph_partition():
    """End-to-end: the SPMD solve on a native graph partition matches the
    single-part solve (partition-layout independence of the solver)."""
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver import Solver

    model = make_cube_model(8, 6, 6, heterogeneous=True)
    cfg = RunConfig(
        partition_method="graph",
        solver=SolverConfig(tol=1e-9, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s8 = Solver(model, cfg, mesh=make_mesh(8), n_parts=8, backend="general")
    s8.solve()
    u8 = s8.displacement_global()

    cfg1 = RunConfig(
        solver=SolverConfig(tol=1e-9, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s1 = Solver(model, cfg1, mesh=make_mesh(1), n_parts=1, backend="general")
    s1.solve()
    u1 = s1.displacement_global()
    np.testing.assert_allclose(u8, u1, rtol=0, atol=1e-6 * np.abs(u1).max())
