"""Structured slab fast path vs dense assembly and vs the general backend."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS, make_mesh
from pcg_mpi_solver_tpu.parallel.structured import (
    StructuredOps,
    device_data_structured,
    partition_structured,
)
from pcg_mpi_solver_tpu.solver.driver import Solver, _data_specs


def to_parts(sp, x_glob):
    out = np.zeros((sp.n_parts, sp.n_loc))
    for p in range(sp.n_parts):
        out[p] = x_glob[sp.dof_gid[p]]
    return out


def to_global(sp, y):
    out = np.zeros(sp.glob_n_dof)
    m = sp.weight > 0
    out[sp.dof_gid[m]] = np.asarray(y)[m]
    return out


@pytest.mark.parametrize("n_parts", [1, 4])
def test_structured_matvec_vs_dense(n_parts):
    model = make_cube_model(8, 3, 5, h=0.5, nu=0.3, heterogeneous=True)
    sp = partition_structured(model, n_parts)
    ops = StructuredOps.from_partition(sp)  # unsharded (roll-based halo)
    data = device_data_structured(sp)

    x = np.random.default_rng(0).normal(size=model.n_dof)
    y = ops.matvec(data, jnp.asarray(to_parts(sp, x)))
    y_ref = model.assemble_csr() @ x
    np.testing.assert_allclose(to_global(sp, y), y_ref, rtol=1e-10, atol=1e-10)
    # every duplicated plane copy fully assembled
    for p in range(n_parts):
        np.testing.assert_allclose(np.asarray(y)[p], y_ref[sp.dof_gid[p]],
                                   rtol=1e-10, atol=1e-10)


def test_structured_diag_vs_assembled():
    model = make_cube_model(4, 3, 3, heterogeneous=True)
    sp = partition_structured(model, 2)
    ops = StructuredOps.from_partition(sp)
    d = ops.diag(device_data_structured(sp))
    np.testing.assert_allclose(to_global(sp, d), model.assemble_diag(), rtol=1e-12)


def test_structured_matvec_sharded_8dev():
    model = make_cube_model(16, 4, 4, heterogeneous=True)
    sp = partition_structured(model, 8)
    mesh = make_mesh(8)
    ops = StructuredOps.from_partition(sp, axis_name=PARTS_AXIS)
    data = device_data_structured(sp)
    P = jax.sharding.PartitionSpec
    f = jax.jit(jax.shard_map(lambda d, v: ops.matvec(d, v), mesh=mesh,
                              in_specs=(_data_specs(data), P(PARTS_AXIS)),
                              out_specs=P(PARTS_AXIS), check_vma=False))
    x = np.random.default_rng(1).normal(size=model.n_dof)
    y = f(data, jnp.asarray(to_parts(sp, x)))
    y_ref = model.assemble_csr() @ x
    np.testing.assert_allclose(to_global(sp, y), y_ref, rtol=1e-9, atol=1e-9)


@pytest.mark.parametrize("mode", ["direct", "mixed"])
def test_structured_solver_matches_general(mode):
    """Full solve through the driver: structured backend == general backend
    (same displacements, comparable iteration count)."""
    model = make_cube_model(8, 4, 4, E=5.0, load="traction", heterogeneous=True)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-9, max_iter=3000, precision_mode=mode),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    mesh = make_mesh(4)
    s_st = Solver(model, cfg, mesh=mesh, n_parts=4, backend="structured")
    assert s_st.backend == "structured"
    r_st = s_st.step(1.0)
    s_gen = Solver(model, cfg, mesh=mesh, n_parts=4, backend="general")
    r_gen = s_gen.step(1.0)
    assert r_st.flag == 0 and r_gen.flag == 0
    u_gen = s_gen.displacement_global()
    np.testing.assert_allclose(s_st.displacement_global(), u_gen,
                               rtol=1e-6, atol=1e-9 * np.abs(u_gen).max())
    assert abs(r_st.iters - r_gen.iters) <= max(3, 0.05 * r_gen.iters)


def test_auto_backend_selection():
    model = make_cube_model(8, 4, 4)
    mesh = make_mesh(4)
    assert Solver(model, RunConfig(), mesh=mesh, n_parts=4).backend == "structured"
    # multi-type model has no grid metadata -> general
    model2 = make_cube_model(8, 4, 4, n_types=2)
    assert Solver(model2, RunConfig(), mesh=mesh, n_parts=4).backend == "general"
    # nx not divisible by parts -> general
    model3 = make_cube_model(6, 4, 4)
    assert Solver(model3, RunConfig(), mesh=mesh, n_parts=4).backend == "general"

def test_chunked_f64_matvec_matches_unchunked():
    """The x-slab-chunked f64 matvec (memory-bounded path for big meshes)
    must agree with the one-shot path exactly."""
    import dataclasses

    from pcg_mpi_solver_tpu.parallel.structured import (
        StructuredOps, device_data_structured, partition_structured)

    model = make_cube_model(12, 6, 5, heterogeneous=True)
    sp = partition_structured(model, 2)
    data = device_data_structured(sp, jnp.float64)
    ops = StructuredOps.from_partition(sp)
    ops_chunked = dataclasses.replace(ops, chunk_threshold=1)
    assert ops_chunked._chunk_planes(jnp.float64) > 0
    x = jnp.asarray(np.random.default_rng(5).normal(size=(2, sp.n_loc)))
    y0 = ops.matvec_local(data, x)
    y1 = ops_chunked.matvec_local(data, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-13, atol=1e-13)


def test_alt_forms_match_gse():
    """The alternative formulations (corner: fusion-friendly, no
    (24, cells) intermediates; gsplit: concat-free accumulating einsums)
    must produce the same matvec as the default gather/einsum/scatter
    form to float tolerance.  The form is pinned per-ops at
    construction, so both formulations are explicit instances."""
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.parallel.structured import (
        StructuredOps, device_data_structured, partition_structured)

    model = make_cube_model(8, 6, 4, heterogeneous=True)
    sp = partition_structured(model, 2)
    data = device_data_structured(sp, jnp.float64)
    ops_gse = StructuredOps.from_partition(sp, form="gse")
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, sp.n_loc)))
    y_gse = np.asarray(ops_gse.matvec(data, x))
    scale = np.abs(y_gse).max()
    for form in ("corner", "gsplit"):
        ops_f = StructuredOps.from_partition(sp, form=form)
        y_f = np.asarray(ops_f.matvec(data, x))
        assert np.abs(y_f - y_gse).max() / scale < 1e-13, form
