"""Element-matrix properties: symmetry, SPD on constrained space, rigid-body
null space, and scaling laws underpinning the pattern-type trick."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu.models.element import (
    HEX_CORNERS,
    hex_mass,
    hex_stiffness,
    hex_strain_mode,
)


def test_stiffness_symmetric():
    Ke = hex_stiffness()
    np.testing.assert_allclose(Ke, Ke.T, atol=1e-12)


def test_stiffness_rigid_body_nullspace():
    """K annihilates the 6 rigid-body modes (3 translations + 3 rotations)."""
    Ke = hex_stiffness(h=2.0, E=3.0, nu=0.3)
    X = HEX_CORNERS * 2.0
    modes = []
    for d in range(3):
        t = np.zeros((8, 3)); t[:, d] = 1.0
        modes.append(t.ravel())
    for axis in range(3):
        r = np.zeros((8, 3))
        a = np.zeros(3); a[axis] = 1.0
        for i in range(8):
            r[i] = np.cross(a, X[i])
        modes.append(r.ravel())
    for m in modes:
        assert np.abs(Ke @ m).max() < 1e-10
    # exactly 6 zero eigenvalues
    w = np.linalg.eigvalsh(Ke)
    assert (np.abs(w) < 1e-10).sum() == 6
    assert w[6] > 1e-8  # rest strictly positive (semi-definite K)


def test_stiffness_scaling_law():
    """Ke(h, E) = E*h*Ke(1, 1): the Ck = E*h pattern-type scaling."""
    Ke1 = hex_stiffness(1.0, 1.0, 0.25)
    Ke2 = hex_stiffness(0.5, 7.0, 0.25)
    np.testing.assert_allclose(Ke2, 7.0 * 0.5 * Ke1, rtol=1e-12, atol=1e-14)


def test_mass_total():
    """Consistent mass sums to rho*h^3 per direction."""
    Me = hex_mass(h=2.0, rho=3.0)
    np.testing.assert_allclose(Me.sum(), 3.0 * 8.0 * 3, rtol=1e-12)
    np.testing.assert_allclose(Me, Me.T, atol=1e-14)


def test_strain_mode_constant_fields():
    """Se reproduces uniform strain states exactly (patch-test property)."""
    Se = hex_strain_mode(h=1.0)
    X = HEX_CORNERS
    # uniaxial stretch u_x = x => eps_xx = 1
    u = np.zeros((8, 3)); u[:, 0] = X[:, 0]
    eps = Se @ u.ravel()
    np.testing.assert_allclose(eps, [1, 0, 0, 0, 0, 0], atol=1e-12)
    # simple shear u_x = y => gamma_xy = 1 (Voigt XX,YY,ZZ,YZ,XZ,XY)
    u = np.zeros((8, 3)); u[:, 0] = X[:, 1]
    eps = Se @ u.ravel()
    np.testing.assert_allclose(eps, [0, 0, 0, 0, 0, 1], atol=1e-12)
