"""Octree capability: graded 2:1 meshes with real multi-type transition
patterns (the reference's actual problem class — partition_mesh.py:1074
pattern types, :420-493 type groups, :546 per-type Ke, sign vectors
pcg_solver.py:277-280).

Covers: generator invariants, reflection/sign canonicalization equivalence,
device matvec vs dense assembly on mixed-d type blocks, PCG vs scipy,
partition-count parity under 8-way SPMD, and a pinned iteration golden."""

import collections

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.octree import (
    canonical_mask, make_octree_model, transition_element)
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.driver import Solver


@pytest.fixture(scope="module")
def model():
    return make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3,
                             load="traction", load_value=1.0)


def test_generator_is_genuinely_multitype(model):
    m = model
    d_set = sorted({3 * lib["n_nodes"] for lib in m.elem_lib.values()})
    assert len(m.elem_lib) >= 4, "expected several transition pattern types"
    assert len(d_set) >= 3, f"expected heterogeneous dofs-per-element, got {d_set}"
    assert d_set[0] == 24 and d_set[-1] > 24
    assert m.elem_sign_flat.any(), "mirrored patterns must carry sign flips"
    assert len(np.unique(m.level)) >= 2, "expected a graded (2:1) mesh"


def test_transition_element_spd_with_rigid_modes():
    """Each pattern Ke is symmetric PSD with EXACTLY 6 zero-energy (rigid
    body) modes — the macro construction must not add spurious modes."""
    m = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3)
    for t, lib in m.elem_lib.items():
        Ke = lib["Ke"]
        assert np.allclose(Ke, Ke.T, atol=1e-12)
        w = np.linalg.eigvalsh(Ke)
        assert np.all(w > -1e-10)
        n_zero = int(np.sum(w < 1e-10 * max(w.max(), 1)))
        assert n_zero == 6, f"type {t}: {n_zero} zero modes"


def test_patch_test_linear_completeness():
    """Homogeneous linear displacement field => zero internal force at every
    interior node: the variable-node basis is conforming across coarse/fine
    interfaces (hanging nodes are real dofs, no constraint residual)."""
    m = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3,
                          incl_stiff=1.0)
    K = m.assemble_csr()
    B = np.array([[0.3, 0.1, 0.0], [0.05, -0.2, 0.1], [0.0, 0.12, 0.25]])
    u = (m.node_coords @ B.T + 0.5).ravel()
    f = K @ u
    c = m.node_coords
    interior = ((c[:, 0] > 0) & (c[:, 0] < c[:, 0].max())
                & (c[:, 1] > 0) & (c[:, 1] < c[:, 1].max())
                & (c[:, 2] > 0) & (c[:, 2] < c[:, 2].max()))
    assert abs(f[np.repeat(interior, 3)]).max() < 1e-12 * abs(f).max() + 1e-13


def test_canonicalized_signs_match_raw_assembly():
    """Reflection canonicalization (fewer types + sign vectors) must produce
    EXACTLY the same global K as one-type-per-raw-mask with no signs — this
    proves the mirrored-pattern sign semantics (pcg_solver.py:277-280)."""
    kw = dict(max_level=2, n_incl=2, seed=3)
    mc = make_octree_model(2, 2, 2, canonicalize=True, **kw)
    mr = make_octree_model(2, 2, 2, canonicalize=False, **kw)
    assert len(mc.elem_lib) < len(mr.elem_lib)
    assert not mr.elem_sign_flat.any()
    Kc, Kr = mc.assemble_csr(), mr.assemble_csr()
    err = abs(Kc - Kr).max()
    assert err < 1e-11 * abs(Kr).max()


def test_face_incidence(model):
    """Interior faces appear exactly twice, boundary faces once (the
    invariant the exporter's Boundary mode relies on,
    export_vtk.py:105-113); subdivided coarse faces are emitted as their 4
    sub-quads so they pair with the fine neighbors' faces."""
    faces = model.faces_flat.reshape(-1, 4)
    cnt = collections.Counter(tuple(sorted(f)) for f in faces)
    hist = collections.Counter(cnt.values())
    assert set(hist) == {1, 2}
    assert hist[1] > 0 and hist[2] > 0


def test_canonical_mask_involution():
    rng = np.random.default_rng(0)
    for m in rng.integers(0, 1 << 18, 50):
        cm, r = canonical_mask(int(m))
        cm2, _ = canonical_mask(cm)
        assert cm2 == cm  # canonical is a fixed point


def _solver(model, n_parts, n_dev=None, tol=1e-8, **kw):
    cfg = RunConfig(
        solver=SolverConfig(tol=tol, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    return Solver(model, cfg, mesh=make_mesh(n_dev or n_parts),
                  n_parts=n_parts, **kw)


def test_matvec_matches_dense_mixed_d_blocks(model):
    """Device matvec on the general path vs scipy assembly — on a model
    whose type blocks have DIFFERENT d (24..51 dofs/elem), proving the
    per-block generality of ops/matvec.py (VERDICT round 1, weak #8)."""
    import jax.numpy as jnp

    K = model.assemble_csr()
    rng = np.random.default_rng(1)
    x = rng.standard_normal(model.n_dof)

    for n_parts in (1, 8):
        s = _solver(model, n_parts)
        data = s.data
        xs = np.zeros((s.pm.n_parts, s.pm.n_loc))
        gid = s.pm.dof_gid
        xs = np.where(gid >= 0, x[np.maximum(gid, 0)], 0.0)
        import jax

        yfn = jax.jit(jax.shard_map(
            lambda d, v: s.ops.matvec(d, v), mesh=s.mesh,
            in_specs=(s._specs, s._part_spec), out_specs=s._part_spec,
            check_vma=False))
        y = np.asarray(yfn(data, jnp.asarray(xs)))
        y_glob = np.zeros(model.n_dof)
        mask = s.owner_mask()
        y_glob[gid[mask]] = y[mask]
        np.testing.assert_allclose(y_glob, K @ x, rtol=1e-9,
                                   atol=1e-10 * abs(K @ x).max())


def test_pcg_matches_scipy(model):
    from scipy.sparse.linalg import spsolve

    s = _solver(model, 1)
    res = s.step(1.0)
    assert res.flag == 0 and res.relres <= 1e-8
    K = model.assemble_csr()
    eff = model.dof_eff
    rhs = (model.F - K @ model.Ud)[eff]
    u_ref = np.array(model.Ud)
    u_ref[eff] += spsolve(K[eff][:, eff].tocsc(), rhs)
    u = s.displacement_global()
    np.testing.assert_allclose(u, u_ref, rtol=1e-5,
                               atol=1e-8 * np.abs(u_ref).max())


def test_partition_parity_8way_spmd(model):
    """Iteration count must not change with the partition count (domain
    decomposition preserves the math) — on the octree model under real
    8-way SPMD."""
    results = {}
    for n_parts in (1, 4, 8):
        s = _solver(model, n_parts)
        results[n_parts] = s.step(1.0)
    for n_parts in (4, 8):
        assert results[n_parts].flag == 0
        assert abs(results[n_parts].iters - results[1].iters) <= 1


# Pinned at round 2 (tol=1e-8, Jacobi, f64 direct, 4 parts); the solution
# checksum guards against silent numerics drift with unchanged iters.
GOLDEN_OCTREE_ITERS = 85
GOLDEN_OCTREE_CHECKSUM = 243.89247971925158


def test_golden_iteration_count(model):
    """Pinned golden for the flagship octree model: numerics drift between
    rounds must fail loudly (VERDICT round 1, missing #5).  If a deliberate
    algorithm change moves this, re-pin with justification."""
    s = _solver(model, 4)
    res = s.step(1.0)
    assert res.flag == 0
    assert abs(res.iters - GOLDEN_OCTREE_ITERS) <= 1, res.iters
    checksum = float(np.abs(s.displacement_global()).sum())
    assert np.isclose(checksum, GOLDEN_OCTREE_CHECKSUM, rtol=1e-6), checksum
