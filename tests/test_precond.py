"""Block-Jacobi (3x3 node-block) preconditioner: masked inversion, block
assembly vs dense K on all three backends, and end-to-end solves.

The reference has only scalar Jacobi (pcg_solver.py:346-352); block-Jacobi
is a beyond-reference capability (BASELINE.json config 4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models import make_cube_model
from pcg_mpi_solver_tpu.models.octree import make_octree_model
from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
from pcg_mpi_solver_tpu.ops.precond import invert_node_blocks
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.parallel.partition import partition_model
from pcg_mpi_solver_tpu.solver.driver import Solver


def dense_node_blocks(model):
    """(n_node, 3, 3) node-diagonal blocks of the assembled global K."""
    K = np.asarray(model.assemble_csr().todense())
    n = model.n_node
    return K.reshape(n, 3, n, 3)[np.arange(n), :, np.arange(n), :]


def gathered_blocks(ops, data, pm):
    """ops.node_block_diag mapped back to global node ids (first copy)."""
    B = np.asarray(ops.node_block_diag(data))
    out = np.zeros((int(pm.node_gid.max()) + 1, 3, 3))
    for p in range(B.shape[0]):
        n = pm.nnode_p[p]
        out[pm.node_gid[p, :n]] = B[p, :n]
    return out


def test_invert_node_blocks_vs_numpy():
    rng = np.random.default_rng(11)
    n = 40
    R = rng.normal(size=(n, 3, 3))
    B = R @ R.transpose(0, 2, 1) + 0.5 * np.eye(3)
    eff = (rng.random((n, 3)) < 0.8).astype(float)
    inv = np.asarray(invert_node_blocks(jnp.asarray(B), jnp.asarray(eff)))
    for i in range(n):
        e = eff[i]
        Bm = B[i] * np.outer(e, e) + np.diag(1.0 - e)
        np.testing.assert_allclose(inv[i], np.linalg.inv(Bm),
                                   rtol=1e-10, atol=1e-12)


def test_invert_degenerate_block_falls_back_to_diag():
    B = np.zeros((2, 3, 3))
    # rank-deficient: [2,4;4,8] block is singular (det exactly 0 in fp)
    B[0] = np.array([[2.0, 4.0, 0.0], [4.0, 8.0, 0.0], [0.0, 0.0, 8.0]])
    B[1] = np.diag([2.0, 0.0, 5.0])          # zero diag entry + det 0
    eff = np.ones((2, 3))
    inv = np.asarray(invert_node_blocks(jnp.asarray(B), jnp.asarray(eff)))
    # fallback is the scalar-Jacobi diagonal inverse of the masked block;
    # a zero diagonal on an effective dof maps to inf (pcg flag-2 contract,
    # matching the scalar path's 1/0)
    np.testing.assert_allclose(inv[0], np.diag([0.5, 0.125, 0.125]), rtol=1e-12)
    np.testing.assert_allclose(inv[1], np.diag([0.5, np.inf, 0.2]), rtol=1e-12)


@pytest.mark.parametrize("n_parts,n_types", [(1, 1), (4, 3)])
def test_node_blocks_vs_dense_general(n_parts, n_types):
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, n_types=n_types,
                            heterogeneous=True)
    pm = partition_model(model, n_parts)
    ops = Ops.from_model(pm)
    got = gathered_blocks(ops, device_data(pm), pm)
    np.testing.assert_allclose(got, dense_node_blocks(model),
                               rtol=1e-10, atol=1e-10)


def test_node_blocks_vs_dense_with_signs():
    model = make_cube_model(3, 2, 2)
    rng = np.random.default_rng(7)
    model.elem_sign_flat = rng.random(model.elem_sign_flat.shape) < 0.3
    pm = partition_model(model, 2)
    got = gathered_blocks(Ops.from_model(pm), device_data(pm), pm)
    np.testing.assert_allclose(got, dense_node_blocks(model),
                               rtol=1e-10, atol=1e-10)


def test_node_blocks_with_springs_vs_dense():
    """Cohesive interface springs land on the (c, c) diagonal entries of
    both endpoint nodes' blocks (_springs_into_blocks flat-offset path),
    with springs crossing partition boundaries."""
    from pcg_mpi_solver_tpu.models.synthetic import make_glued_blocks_model

    model = make_glued_blocks_model(2, 3, 2, 2, E=3.0, penalty=50.0,
                                    kt_factor=0.5)
    # split along y: springs stay part-internal, so the node-contiguous
    # layout (and hence block3) survives; an interface-splitting partition
    # pulls node-less ghost dofs in and block3 raises by design
    elem_part = (model.sctrs[:, 1] > 1.0).astype(np.int32)
    pm = partition_model(model, 2, elem_part=elem_part)
    assert pm.spr_a is not None and pm.ell is not None
    got = gathered_blocks(Ops.from_model(pm), device_data(pm), pm)
    np.testing.assert_allclose(got, dense_node_blocks(model),
                               rtol=1e-10, atol=1e-10)


def test_block3_solve_with_springs():
    from pcg_mpi_solver_tpu.models.synthetic import make_glued_blocks_model

    model = make_glued_blocks_model(2, 2, 2, 2, E=5.0, penalty=100.0)
    elem_part = (model.sctrs[:, 1] > 1.0).astype(np.int32)  # see above
    us = {}
    for precond in ("jacobi", "block3"):
        cfg = RunConfig(
            solver=SolverConfig(tol=1e-8, max_iter=2000, precond=precond),
            time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
        )
        s = Solver(model, cfg, mesh=make_mesh(2), n_parts=2,
                   elem_part=elem_part)
        res = s.step(1.0)
        assert res.flag == 0, (precond, res)
        us[precond] = s.displacement_global()
    np.testing.assert_allclose(us["block3"], us["jacobi"], rtol=1e-5,
                               atol=1e-8 * np.abs(us["jacobi"]).max())


def test_node_blocks_vs_dense_hybrid_octree():
    from pcg_mpi_solver_tpu.parallel.hybrid import (
        HybridOps, device_data_hybrid, partition_hybrid)

    model = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3,
                              load="traction", load_value=1.0)
    hp = partition_hybrid(model, 2)
    ops = HybridOps.from_hybrid(hp)
    got = gathered_blocks(ops, device_data_hybrid(hp), hp.pm)
    np.testing.assert_allclose(got, dense_node_blocks(model),
                               rtol=1e-10, atol=1e-10)


def test_node_blocks_vs_dense_structured():
    from pcg_mpi_solver_tpu.parallel.structured import (
        StructuredOps, device_data_structured, partition_structured)

    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, heterogeneous=True)
    sp = partition_structured(model, 2)
    ops = StructuredOps.from_partition(sp)
    B = np.asarray(ops.node_block_diag(device_data_structured(sp)))
    ref = dense_node_blocks(model)
    out = np.zeros_like(ref)
    for p in range(B.shape[0]):
        out[sp.node_gid[p]] = B[p]           # assembled: copies agree
    np.testing.assert_allclose(out, ref, rtol=1e-10, atol=1e-10)


def _solve(model, *, precond, backend="general", mode="direct", n_dev=4,
           iters_per_dispatch=0, tol=1e-8):
    cfg = RunConfig(
        solver=SolverConfig(tol=tol, max_iter=2000, precision_mode=mode,
                            precond=precond,
                            iters_per_dispatch=iters_per_dispatch),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(n_dev), n_parts=n_dev,
               backend=backend)
    res = s.step(1.0)
    return res, s.displacement_global()


def test_block3_solve_matches_jacobi_and_cuts_iters():
    model = make_cube_model(6, 5, 5, h=0.5, nu=0.3, heterogeneous=True,
                            seed=0)
    rj, uj = _solve(model, precond="jacobi")
    rb, ub = _solve(model, precond="block3")
    assert rj.flag == 0 and rb.flag == 0
    np.testing.assert_allclose(ub, uj, rtol=1e-6, atol=1e-9 * np.abs(uj).max())
    # the block preconditioner must not be weaker than scalar Jacobi
    assert rb.iters <= rj.iters, (rb.iters, rj.iters)


def test_block3_mixed_and_chunked_paths():
    model = make_cube_model(6, 4, 4, heterogeneous=True)
    r0, u0 = _solve(model, precond="block3", mode="direct")
    rm, um = _solve(model, precond="block3", mode="mixed")
    rc, uc = _solve(model, precond="block3", mode="mixed",
                    iters_per_dispatch=15)
    assert r0.flag == 0 and rm.flag == 0 and rc.flag == 0
    scale = np.abs(u0).max()
    assert np.abs(um - u0).max() / scale < 1e-6
    assert np.abs(uc - u0).max() / scale < 1e-6


def test_block3_structured_backend_solve():
    model = make_cube_model(8, 4, 4, heterogeneous=True)
    rs, us = _solve(model, precond="block3", backend="structured", n_dev=8)
    rg, ug = _solve(model, precond="block3", backend="general", n_dev=8)
    assert rs.flag == 0 and rg.flag == 0
    assert rs.iters == pytest.approx(rg.iters, abs=2)
    np.testing.assert_allclose(us, ug, rtol=1e-6,
                               atol=1e-9 * np.abs(ug).max())


def test_block3_hybrid_octree_solve():
    model = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3,
                              load="traction", load_value=1.0)
    rj, uj = _solve(model, precond="jacobi", backend="hybrid", n_dev=2)
    rb, ub = _solve(model, precond="block3", backend="hybrid", n_dev=2)
    assert rj.flag == 0 and rb.flag == 0
    # two different preconditioners at tol=1e-8: agreement to solver tol
    np.testing.assert_allclose(ub, uj, rtol=1e-4,
                               atol=1e-7 * np.abs(uj).max())
    assert rb.iters <= rj.iters, (rb.iters, rj.iters)


def test_block3_ill_conditioned_block_not_degraded():
    """A valid but stiff SPD block whose normalized det sits below f32 eps
    (two stiffness ratios of ~3e-4: det ~9e-8) must get the true block
    inverse, not the silent scalar-Jacobi fallback (ADVICE r2).  The block
    is ROTATED so the scalar fallback is measurably wrong — a diagonal
    test block would pass either way."""
    rng = np.random.default_rng(11)
    q, _ = np.linalg.qr(rng.standard_normal((3, 3)))
    spec = np.array([1.0, 3e-4, 3e-4])
    d = (q * spec) @ q.T                                # SPD, cond ~3e3
    B = jnp.asarray(d.astype(np.float32))[None, None]   # (1, 1, 3, 3)
    eff = jnp.ones((1, 1, 3), jnp.float32)
    inv = np.asarray(invert_node_blocks(B, eff))[0, 0]
    # true block inverse reconstructs I to ~cond * eps32; the scalar
    # fallback on a rotated block has O(1) reconstruction error
    assert np.abs(d @ inv - np.eye(3)).max() < 5e-3
    # a numerically singular block still takes the safe scalar branch
    d2 = np.zeros((3, 3), np.float32)
    inv2 = np.asarray(invert_node_blocks(
        jnp.asarray(d2)[None, None], eff))[0, 0]
    assert np.isinf(inv2).any()
