"""Timing/observability: TimeData schema (compile estimate, export bucket,
load-unbalance stats — reference configTimeRecData, file_operations.py:72-172)
plus the probe-plot PNG and the jax.profiler trace hook."""

import os

import numpy as np

from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver import Solver
from pcg_mpi_solver_tpu.utils.io import RunStore


def test_time_data_schema_and_store_roundtrip(tmp_path):
    model = make_cube_model(4, 4, 4, heterogeneous=True)
    cfg = RunConfig(
        scratch_path=str(tmp_path),
        solver=SolverConfig(tol=1e-8, max_iter=300),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 0.5, 1.0],
                                       plot_flag=True, probe_dofs=(3, 7)),
    )
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    store = RunStore(cfg.result_path)
    s.solve(store=store)

    td = s.time_data(t_prep=0.1)
    assert td["Mean_CalcTime"] > 0
    assert td["Compile_Time_Est"] >= 0
    assert td["Export_Time"] > 0
    lu = td["LoadUnbalanceData"]
    assert lu["ElemsPerPart"].sum() == model.n_elem
    assert lu["DofsPerPart"].shape == (4,)
    assert lu["MaxByMeanDofs"] >= 1.0
    assert 0.0 <= lu["IfaceDofFrac"] <= 1.0
    assert len(td["StepTimes"]) == 2

    # round-trips through the store (npz + mat with the nested dict)
    store.write_time_data(4, td)
    back = store.read_time_data(4)
    assert back["LoadUnbalanceData"]["MaxByMeanDofs"] == lu["MaxByMeanDofs"]
    np.testing.assert_array_equal(back["Iter"], td["Iter"])

    # probe plot artifacts: npz + mat + png
    assert os.path.exists(f"{cfg.plot_path}/model_PlotData.npz")
    assert os.path.exists(f"{cfg.plot_path}/model_PlotData.mat")
    assert os.path.exists(f"{cfg.plot_path}/model_PlotData.png")


def test_comm_split_measured_nonzero_on_8way(tmp_path):
    """The calc vs comm-wait attribution (the reference's primary scaling
    diagnostic, pcg_solver.py:631-641) must produce a nonzero, plausible
    collective share on a real 8-way SPMD run."""
    model = make_cube_model(6, 4, 4, heterogeneous=True)
    cfg = RunConfig(
        scratch_path=str(tmp_path),
        solver=SolverConfig(tol=1e-8, max_iter=300),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(8), n_parts=8)
    store = RunStore(cfg.result_path)
    s.solve(store=store)

    # Timing-based: on near-free virtual-CPU psums scheduler noise can clamp
    # a single measurement to 0 — retry a few times before declaring the
    # probe broken.
    for _ in range(3):
        split = s.measure_comm_split(n_iters=20)
        assert split["full_s_per_iter"] > 0
        if split["comm_frac"] > 0.0:
            break
    assert 0.0 < split["comm_frac"] < 1.0

    td = s.time_data(t_prep=0.0, comm_split=split)
    assert td["Mean_CommWaitTime"] > 0
    assert np.isclose(td["Mean_CalcTime"] + td["Mean_CommWaitTime"],
                      float(np.sum(s.step_times)))
    # the solve() export path records the split in the stored TimeData
    td_stored = store.read_time_data(8)
    assert "CommProbe" in td_stored


def test_profile_trace_written(tmp_path):
    model = make_cube_model(3, 3, 3)
    prof = str(tmp_path / "trace")
    cfg = RunConfig(
        scratch_path=str(tmp_path),
        profile_dir=prof,
        solver=SolverConfig(tol=1e-6, max_iter=100),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                       export_flag=False),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    s.solve()
    # trace directory exists and is non-empty
    found = [os.path.join(r, f) for r, _, fs in os.walk(prof) for f in fs]
    assert found, "profiler trace produced no files"
