"""Timing/observability: TimeData schema (compile estimate, export bucket,
load-unbalance stats — reference configTimeRecData, file_operations.py:72-172)
plus the probe-plot PNG and the jax.profiler trace hook."""

import os

import numpy as np

from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver import Solver
from pcg_mpi_solver_tpu.utils.io import RunStore


def test_time_data_schema_and_store_roundtrip(tmp_path):
    model = make_cube_model(4, 4, 4, heterogeneous=True)
    cfg = RunConfig(
        scratch_path=str(tmp_path),
        solver=SolverConfig(tol=1e-8, max_iter=300),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 0.5, 1.0],
                                       plot_flag=True, probe_dofs=(3, 7)),
    )
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    store = RunStore(cfg.result_path)
    s.solve(store=store)

    td = s.time_data(t_prep=0.1)
    assert td["Mean_CalcTime"] > 0
    assert td["Compile_Time_Est"] >= 0
    assert td["Export_Time"] > 0
    lu = td["LoadUnbalanceData"]
    assert lu["ElemsPerPart"].sum() == model.n_elem
    assert lu["DofsPerPart"].shape == (4,)
    assert lu["MaxByMeanDofs"] >= 1.0
    assert 0.0 <= lu["IfaceDofFrac"] <= 1.0
    assert len(td["StepTimes"]) == 2

    # round-trips through the store (npz + mat with the nested dict)
    store.write_time_data(4, td)
    back = store.read_time_data(4)
    assert back["LoadUnbalanceData"]["MaxByMeanDofs"] == lu["MaxByMeanDofs"]
    np.testing.assert_array_equal(back["Iter"], td["Iter"])

    # probe plot artifacts: npz + mat + png
    assert os.path.exists(f"{cfg.plot_path}/model_PlotData.npz")
    assert os.path.exists(f"{cfg.plot_path}/model_PlotData.mat")
    assert os.path.exists(f"{cfg.plot_path}/model_PlotData.png")


def test_profile_trace_written(tmp_path):
    model = make_cube_model(3, 3, 3)
    prof = str(tmp_path / "trace")
    cfg = RunConfig(
        scratch_path=str(tmp_path),
        profile_dir=prof,
        solver=SolverConfig(tol=1e-6, max_iter=100),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                       export_flag=False),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    s.solve()
    # trace directory exists and is non-empty
    found = [os.path.join(r, f) for r, _, fs in os.walk(prof) for f in fs]
    assert found, "profiler trace produced no files"
