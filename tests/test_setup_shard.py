"""Sharded setup path (ISSUE 14): parallel per-part partition builds,
the two-level element split, slab-streamed MDF ingest, the
shard-addressed partition cache (+ legacy monolithic shim), the MG
replication cutoff, and the concurrent-eviction bugfix.

The REAL multi-process leg (4-way jax.distributed warm start reading
only per-part entries, bit-identical to the monolithic cold build) is
at the bottom — everything above runs in-process via ``part_range`` +
layout injection, which the multi-process path shares."""

import dataclasses
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.mdf import (IngestStats, read_mdf,
                                           read_mdf_slab, write_mdf)
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.parallel import partition as P
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.parallel.partition import BUILD_CALLS
from pcg_mpi_solver_tpu.solver.driver import Solver


def _rows_equal(full, shard, lo, hi, n_parts, skip=("elem_part",)):
    """Assert every (P, ...) array's [lo, hi) rows match between two
    partition objects (and type blocks, when present)."""
    for f in dataclasses.fields(full):
        if f.name in ("type_blocks", "layout", "part_range") + tuple(skip):
            continue
        a, b = getattr(full, f.name), getattr(shard, f.name)
        if isinstance(a, np.ndarray) and a.ndim >= 1 \
                and a.shape[0] == n_parts:
            assert np.array_equal(a[lo:hi], b[lo:hi]), f.name
        elif isinstance(a, np.ndarray):
            assert np.array_equal(a, b), f.name
    for ta, tb in zip(getattr(full, "type_blocks", []) or [],
                      getattr(shard, "type_blocks", []) or []):
        for ff in dataclasses.fields(ta):
            va, vb = getattr(ta, ff.name), getattr(tb, ff.name)
            if isinstance(va, np.ndarray) and va.ndim >= 1 \
                    and va.shape[0] == n_parts:
                assert np.array_equal(va[lo:hi], vb[lo:hi]), ff.name


# ----------------------------------------------------------------------
# two-level split
# ----------------------------------------------------------------------

def test_two_level_partition_degenerates_and_balances():
    m = make_cube_model(8, 6, 5, heterogeneous=True)
    assert np.array_equal(P.two_level_partition(m.sctrs, 8, 1),
                          P.rcb_partition(m.sctrs, 8))
    ep = P.two_level_partition(m.sctrs, 8, 4)
    assert np.array_equal(ep, P.two_level_partition(m.sctrs, 8, 4))
    counts = np.bincount(ep, minlength=8)
    assert counts.min() > 0 and counts.max() <= 2 * counts.min()
    with pytest.raises(ValueError):
        P.two_level_partition(m.sctrs, 8, 3)


def test_two_level_refine_local_matches_full_on_refined_slabs():
    m = make_cube_model(8, 6, 5, heterogeneous=True)
    full = P.two_level_partition(m.sctrs, 8, 4)
    for s in range(4):
        part = P.two_level_partition(m.sctrs, 8, 4, refine=[s])
        sel = np.isin(full, [2 * s, 2 * s + 1])
        assert np.array_equal(part[sel], full[sel])


def test_slab_local_parts_matches_two_level():
    """slab_local_parts on a slab's centroid subset reproduces the full
    two-level map's labels for that slab (the slab-ingest contract)."""
    m = make_cube_model(8, 6, 5, heterogeneous=True)
    full = P.two_level_partition(m.sctrs, 8, 4)
    slab = P.coarse_slab_cut(m.sctrs, 4)
    for s in range(4):
        ids = np.where(slab == s)[0]
        ep_local, rng = P.slab_local_parts(m.sctrs[ids], 8, 4, s)
        assert rng == (2 * s, 2 * s + 2)
        assert np.array_equal(ep_local, full[ids])


# ----------------------------------------------------------------------
# part_range builds (general + structured)
# ----------------------------------------------------------------------

def test_partition_part_range_rows_match_full_build():
    m = make_cube_model(6, 5, 4, heterogeneous=True)
    full = P.partition_model(m, 8)
    for lo, hi in ((0, 2), (2, 4), (4, 8)):
        sh = P.partition_model(m, 8, part_range=(lo, hi),
                               layout=full.layout)
        _rows_equal(full, sh, lo, hi, 8)
        # unbuilt rows stay at padding values
        assert (sh.dof_gid[:lo] == -1).all() and (sh.weight[:lo] == 0).all()
    assert full.part_range == (0, 8)


def test_partition_part_range_work_scales_down():
    """Building 2 of 8 parts must cost well under the full build — the
    cold-path scaling claim, measured comm-free (layout injected)."""
    m = make_cube_model(48, 16, 16, heterogeneous=True)
    full_t = shard_t = None
    for _ in range(2):                       # best-of-2: CI noise
        t0 = time.perf_counter()
        full = P.partition_model(m, 8, method="slab2", slab2_slabs=4)
        t_full = time.perf_counter() - t0
        t0 = time.perf_counter()
        P.partition_model(m, 8, method="slab2", slab2_slabs=4,
                          part_range=(0, 2), layout=full.layout)
        t_shard = time.perf_counter() - t0
        full_t = t_full if full_t is None else min(full_t, t_full)
        shard_t = t_shard if shard_t is None else min(shard_t, t_shard)
    assert full_t / shard_t >= 1.5, (full_t, shard_t)


def test_structured_part_range_rows_match_full_build():
    from pcg_mpi_solver_tpu.parallel.structured import partition_structured

    m = make_cube_model(8, 4, 4)
    full = partition_structured(m, 8)
    sh = partition_structured(m, 8, part_range=(2, 6))
    for f in dataclasses.fields(full):
        a, b = getattr(full, f.name), getattr(sh, f.name)
        if isinstance(a, np.ndarray) and a.ndim >= 1 and a.shape[0] == 8:
            assert np.array_equal(a[2:6], b[2:6]), f.name
    assert (sh.dof_gid[:2] == -1).all()


# ----------------------------------------------------------------------
# shard-addressed cache
# ----------------------------------------------------------------------

def _cfg(cache_dir="", **solver_kw):
    kw = dict(tol=1e-8, max_iter=500)
    kw.update(solver_kw)
    return RunConfig(cache_dir=str(cache_dir), solver=SolverConfig(**kw),
                     time_history=TimeHistoryConfig(
                         time_step_delta=[0.0, 1.0], export_flag=False))


def test_shard_cache_round_trip_bit_identical(tmp_path):
    """Cold build publishes glue + one entry per part; a fresh solver
    warm-starts with ZERO partition work and a bit-identical solve."""
    m = make_cube_model(6, 5, 4, heterogeneous=True)
    cfg = _cfg(tmp_path)
    s1 = Solver(m, cfg, mesh=make_mesh(8), n_parts=8, backend="general")
    assert s1.setup_cache == "cold"
    r1 = s1.step(1.0)
    entries = [f for f in os.listdir(tmp_path / "partition")
               if f.endswith(".zpkl")]
    assert len(entries) == 9          # 8 per-part + 1 glue
    b0 = dict(BUILD_CALLS)
    s2 = Solver(m, cfg, mesh=make_mesh(8), n_parts=8, backend="general")
    assert s2.setup_cache == "warm"
    assert BUILD_CALLS == b0          # zero partitioning work
    r2 = s2.step(1.0)
    assert (r1.flag, r1.iters) == (r2.flag, r2.iters)
    np.testing.assert_array_equal(s1.displacement_global(),
                                  s2.displacement_global())


def test_shard_cache_loads_only_requested_parts(tmp_path):
    """cached_partition_shards reads ONLY the entries named in
    part_keys — the each-host-reads-its-slice contract, asserted at the
    file level."""
    from pcg_mpi_solver_tpu.cache import keys as ckeys
    from pcg_mpi_solver_tpu.cache import partition_cache as pc
    from pcg_mpi_solver_tpu.cache.shards import (join_partition,
                                                 split_partition)

    m = make_cube_model(6, 5, 4, heterogeneous=True)
    full = P.partition_model(m, 8)
    kw = dict(n_parts=8, backend="general", dtype="float64", method="rcb")
    glue_key = ckeys.partition_glue_key("fp", **kw)
    all_keys = {p: ckeys.partition_shard_key("fp", part_idx=p, **kw)
                for p in range(8)}
    pc.cached_partition_shards(
        str(tmp_path), glue_key=glue_key, part_keys=all_keys,
        builder=lambda: full, split=split_partition, join=join_partition)
    opened = []
    orig = pc.load_partition

    def spy(cache_dir, key):
        opened.append(key)
        return orig(cache_dir, key)

    pc.load_partition = spy
    try:
        sub_keys = {p: all_keys[p] for p in (2, 3)}
        pm = pc.cached_partition_shards(
            str(tmp_path), glue_key=glue_key, part_keys=sub_keys,
            builder=lambda: pytest.fail("warm hit must not build"),
            split=split_partition, join=join_partition)
    finally:
        pc.load_partition = orig
    assert set(opened) == {glue_key, all_keys[2], all_keys[3]}
    _rows_equal(full, pm, 2, 4, 8)
    # ...and the joined subset is bit-identical to a cold part_range
    # build of the same parts (warm == cold sharded)
    cold = P.partition_model(m, 8, part_range=(2, 4), layout=full.layout)
    for f in dataclasses.fields(cold):
        if f.name in ("type_blocks", "layout", "part_range", "elem_part"):
            continue
        a, b = getattr(cold, f.name), getattr(pm, f.name)
        if isinstance(a, np.ndarray):
            assert np.array_equal(a, b), f.name


def test_legacy_monolithic_entry_loads_via_shim(tmp_path):
    """A monolithic entry (the pre-ISSUE-14 layout) still serves warm
    starts when shard entries are absent."""
    from pcg_mpi_solver_tpu.cache import keys as ckeys
    from pcg_mpi_solver_tpu.cache import partition_cache as pc
    from pcg_mpi_solver_tpu.cache.shards import (join_partition,
                                                 split_partition)

    m = make_cube_model(6, 5, 4, heterogeneous=True)
    full = P.partition_model(m, 8)
    legacy_key = ckeys.partition_cache_key(
        "fp", n_parts=8, backend="general", dtype="float64", method="rcb")
    pc.store_partition(str(tmp_path), legacy_key, full)
    kw = dict(n_parts=8, backend="general", dtype="float64", method="rcb")
    pm = pc.cached_partition_shards(
        str(tmp_path),
        glue_key=ckeys.partition_glue_key("fp", **kw),
        part_keys={p: ckeys.partition_shard_key("fp", part_idx=p, **kw)
                   for p in range(8)},
        builder=lambda: pytest.fail("legacy shim must not rebuild"),
        split=split_partition, join=join_partition,
        legacy_key=legacy_key)
    _rows_equal(full, pm, 0, 8, 8)


def test_mg_hierarchy_shard_cached(tmp_path):
    """precond='mg' warm starts skip the host hierarchy rebuild: the
    replicated levels live in the glue entry, fine transfers per part."""
    from pcg_mpi_solver_tpu.ops import mg as mgmod

    m = make_cube_model(8, 4, 4, heterogeneous=True)
    cfg = _cfg(tmp_path, precond="mg")
    s1 = Solver(m, cfg, mesh=make_mesh(8), n_parts=8)
    r1 = s1.step(1.0)
    calls = {"n": 0}
    orig = mgmod.build_mg_host

    def spy(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    mgmod.build_mg_host = spy
    try:
        s2 = Solver(m, cfg, mesh=make_mesh(8), n_parts=8)
    finally:
        mgmod.build_mg_host = orig
    assert calls["n"] == 0            # hierarchy came from the cache
    assert s2.setup_cache == "warm"
    r2 = s2.step(1.0)
    assert (r1.flag, r1.iters) == (r2.flag, r2.iters)
    np.testing.assert_array_equal(s1.displacement_global(),
                                  s2.displacement_global())


def test_mg_cache_rekeys_across_partition_methods(tmp_path):
    """The MG fine transfers are laid out in the PARTITION's node
    order — a hierarchy cached against one partition method must not
    warm-serve another (review finding: the mg key must carry the
    partition identity)."""
    m = make_cube_model(8, 4, 4, heterogeneous=True)

    def cfg(method):
        c = _cfg(tmp_path, precond="mg")
        c.partition_method = method
        return c

    s1 = Solver(m, cfg("rcb"), mesh=make_mesh(8), n_parts=8,
                backend="general")
    assert s1.step(1.0).flag == 0
    s2 = Solver(m, cfg("slab2"), mesh=make_mesh(8), n_parts=8,
                backend="general")
    assert s2.setup_cache == "cold"       # no stale cross-partition hit
    assert s2.step(1.0).flag == 0


def test_evict_lru_tolerates_concurrent_deletion(tmp_path, monkeypatch):
    """ISSUE 14 bugfix: another process deleting an entry between
    listdir and stat/remove must not abort this process's eviction."""
    from pcg_mpi_solver_tpu.cache import partition_cache as pc

    d = tmp_path / "partition"
    d.mkdir()
    for i in range(4):
        (d / f"e{i}.zpkl").write_bytes(b"x" * 1000)
        os.utime(d / f"e{i}.zpkl", (i, i))    # e0 oldest
    real_stat = os.stat

    def racing_stat(path, *a, **kw):
        if str(path).endswith("e1.zpkl"):
            raise FileNotFoundError(path)     # concurrently deleted
        return real_stat(path, *a, **kw)

    monkeypatch.setattr(os, "stat", racing_stat)
    pc.evict_lru(str(d), keep=str(d / "e3.zpkl"), cap_bytes=1500)
    # eviction proceeded past the racing entry: oldest survivors gone
    assert not (d / "e0.zpkl").exists()
    assert (d / "e3.zpkl").exists()

    # cache-stats over a racing directory stays standing too
    monkeypatch.setattr(os, "stat", racing_stat)
    stats = pc.cache_stats(str(tmp_path))
    assert stats["partition"]["entries"] >= 1


def test_evict_lru_tolerates_racing_remove(tmp_path, monkeypatch):
    from pcg_mpi_solver_tpu.cache import partition_cache as pc

    d = tmp_path / "partition"
    d.mkdir()
    for i in range(3):
        (d / f"e{i}.zpkl").write_bytes(b"x" * 1000)
        os.utime(d / f"e{i}.zpkl", (i, i))
    real_remove = os.remove

    def racing_remove(path, *a, **kw):
        if str(path).endswith("e0.zpkl"):
            real_remove(path)                 # someone else got it first
            raise FileNotFoundError(path)
        return real_remove(path, *a, **kw)

    monkeypatch.setattr(os, "remove", racing_remove)
    pc.evict_lru(str(d), keep=str(d / "e2.zpkl"), cap_bytes=1000)
    assert not (d / "e1.zpkl").exists()       # continued past the race
    assert (d / "e2.zpkl").exists()


# ----------------------------------------------------------------------
# slab-streamed MDF ingest
# ----------------------------------------------------------------------

def test_read_mdf_slab_union_and_bounded_memory(tmp_path):
    m = make_cube_model(8, 6, 5, heterogeneous=True)
    write_mdf(m, str(tmp_path))
    n_slabs = 4
    full_bytes = (m.elem_nodes_flat.nbytes + m.elem_dofs_flat.nbytes
                  + m.node_coords.nbytes + 4 * m.F.nbytes
                  + m.sctrs.nbytes)
    seen = []
    for q in range(n_slabs):
        st = IngestStats()
        slab = read_mdf_slab(str(tmp_path), q, n_slabs, chunk_elems=64,
                             stats=st)
        assert st.peak_bytes < full_bytes / 2      # bounded peak
        assert slab.glob_n_elem == m.n_elem
        seen.append(np.asarray(slab.elem_ids))
        # slab element content matches the full model at the global ids
        e = slab.elem_ids
        np.testing.assert_array_equal(slab.ck, m.ck[e])
        np.testing.assert_array_equal(slab.sctrs, m.sctrs[e])
        # sparse nodal restriction serves the referenced global ids
        some = slab.elem_dofs_flat[:50]
        np.testing.assert_array_equal(slab.F[some], m.F[some])
    ids = np.sort(np.concatenate(seen))
    np.testing.assert_array_equal(ids, np.arange(m.n_elem))


def test_slab_partition_matches_full_build(tmp_path):
    """The full slab-ingest pipeline: each slab's partition shard (built
    from ONLY its slab's data, layout injected) is bit-identical to the
    full in-memory build's rows."""
    m = make_cube_model(8, 6, 5, heterogeneous=True)
    write_mdf(m, str(tmp_path))
    n_parts, n_slabs = 8, 4
    full = P.partition_model(m, n_parts, method="slab2",
                             slab2_slabs=n_slabs)
    for q in range(n_slabs):
        slab = read_mdf_slab(str(tmp_path), q, n_slabs)
        ep, rng = P.slab_local_parts(slab.sctrs, n_parts, n_slabs, q)
        pm = P.partition_model(slab, n_parts, elem_part=ep,
                               part_range=rng, layout=full.layout)
        _rows_equal(full, pm, rng[0], rng[1], n_parts)


def test_read_mdf_slab_rejects_unseparable_models(tmp_path):
    from pcg_mpi_solver_tpu.models.synthetic import make_glued_blocks_model

    m = make_glued_blocks_model(3, 3, 4, 4)
    write_mdf(m, str(tmp_path / "glued"))
    with pytest.raises(NotImplementedError):
        read_mdf_slab(str(tmp_path / "glued"), 0, 2)


def test_sparsevec_strict_and_fill():
    from pcg_mpi_solver_tpu.models.model_data import SparseVec

    v = SparseVec(np.array([2, 5, 9]), np.array([1.0, 2.0, 3.0]), 12)
    np.testing.assert_array_equal(v[np.array([5, 2, 9])], [2.0, 1.0, 3.0])
    np.testing.assert_array_equal(v[np.array([0, 5])], [0.0, 2.0])
    # a scalar lookup returns a SCALAR, like a dense array's
    assert np.ndim(v[5]) == 0 and float(v[5]) == 2.0
    strict = SparseVec(np.array([2, 5]), np.array([1.0, 2.0]), 12,
                       strict=True)
    with pytest.raises(IndexError):
        strict[np.array([3])]
    np.testing.assert_array_equal(v.materialize()[[2, 5, 9]],
                                  [1.0, 2.0, 3.0])


def test_ragged_index_handles_zero_length_slices():
    from pcg_mpi_solver_tpu.models.mdf import _ragged_index

    got = _ragged_index(np.array([10, 50, 20]), np.array([2, 0, 3]))
    np.testing.assert_array_equal(got, [10, 11, 20, 21, 22])
    got = _ragged_index(np.array([5, 9]), np.array([3, 0]))
    np.testing.assert_array_equal(got, [5, 6, 7])
    assert len(_ragged_index(np.array([3]), np.array([0]))) == 0


def test_sparsevec_content_hashes_into_model_fingerprint(tmp_path):
    """Slab views differing only in NODAL data (loads/coords live in
    SparseVecs) must fingerprint differently — a repr()-level hash
    would collide them in the partition cache (review finding)."""
    from pcg_mpi_solver_tpu.cache.keys import model_fingerprint

    m = make_cube_model(6, 4, 4, heterogeneous=True)
    write_mdf(m, str(tmp_path))
    a = read_mdf_slab(str(tmp_path), 0, 2)
    b = read_mdf_slab(str(tmp_path), 0, 2)
    assert model_fingerprint(a) == model_fingerprint(b)
    b.F.vals = b.F.vals + 1.0          # same topology, different loads
    assert model_fingerprint(a) != model_fingerprint(b)


def test_read_mdf_slab_detects_legacy_nodes_layout(tmp_path):
    """A pre-fix row-major nodes.bin must be detected via the
    NodeCoordVec cross-check (like read_mdf), not silently transposed."""
    m = make_cube_model(4, 4, 4)
    write_mdf(m, str(tmp_path))
    # rewrite nodes.bin in the LEGACY row-major layout
    m.node_coords.astype(np.float64).ravel().tofile(
        str(tmp_path / "nodes.bin"))
    slab = read_mdf_slab(str(tmp_path), 0, 2)
    some = np.asarray(slab.elem_nodes_flat[:20])
    np.testing.assert_array_equal(slab.node_coords[some],
                                  m.node_coords[some])
    # garbage that matches NEITHER layout fails loudly
    rng = np.random.default_rng(0)
    rng.permutation(m.node_coords.ravel()).tofile(
        str(tmp_path / "nodes.bin"))
    with pytest.raises(ValueError, match="neither"):
        read_mdf_slab(str(tmp_path), 0, 2)


def test_mdf_fingerprint_streams_and_detects_edits(tmp_path):
    """The slab-cache key contract: every process derives the identical
    bundle hash without materializing the model, and any content edit
    re-keys."""
    from pcg_mpi_solver_tpu.cache.keys import mdf_fingerprint

    m = make_cube_model(4, 4, 4)
    write_mdf(m, str(tmp_path))
    fp1 = mdf_fingerprint(str(tmp_path))
    assert fp1 == mdf_fingerprint(str(tmp_path))
    with open(tmp_path / "Ck.bin", "r+b") as f:
        f.seek(0)
        f.write(b"\xff")
    assert mdf_fingerprint(str(tmp_path)) != fp1


# ----------------------------------------------------------------------
# MG replication cutoff
# ----------------------------------------------------------------------

def test_mg_replication_cutoff_truncates_and_rejects():
    from pcg_mpi_solver_tpu.ops.mg import (MGSetupError,
                                           apply_replication_cutoff,
                                           level_replicated_dofs)

    dims = [(16, 16, 16), (8, 8, 8), (4, 4, 4)]
    sizes = level_replicated_dofs(dims)
    assert sizes[0] == 3 * 17 ** 3
    # no cutoff / generous cutoff: untouched
    assert apply_replication_cutoff(dims, 0, 0) == dims
    assert apply_replication_cutoff(dims, 0, sum(sizes)) == dims
    # tight cutoff: auto-depth truncates
    kept = apply_replication_cutoff(dims, 0, sizes[0] + sizes[1])
    assert kept == dims[:2]
    # first level over the cutoff: NAMED rejection
    with pytest.raises(MGSetupError, match="mg_max_replicated_dofs"):
        apply_replication_cutoff(dims, 0, sizes[0] - 1)
    # explicit mg_levels that cannot fit: NAMED rejection, not silent
    # truncation
    with pytest.raises(MGSetupError, match="mg_levels"):
        apply_replication_cutoff(dims, 3, sizes[0] + sizes[1])


def test_mg_replication_cutoff_in_build_and_preflight():
    from pcg_mpi_solver_tpu.ops.mg import MGSetupError, build_mg_host
    from pcg_mpi_solver_tpu.validate.preflight import (
        _check_mg_replication)

    m = make_cube_model(8, 8, 8)
    pm = P.partition_model(m, 1)
    # tight cutoff truncates auto depth (8^3 -> only the 4^3 level fits)
    setup = build_mg_host(m, pm, max_replicated_dofs=3 * 5 ** 3)
    assert setup.meta["levels"] == 1
    with pytest.raises(MGSetupError, match="mg_max_replicated_dofs"):
        build_mg_host(m, pm, max_replicated_dofs=10)

    scfg = SolverConfig(precond="mg", mg_max_replicated_dofs=10)
    chk = _check_mg_replication(m, scfg)
    assert chk.status == "fail" and "mg_max_replicated_dofs" in chk.detail
    scfg = SolverConfig(precond="mg", mg_max_replicated_dofs=3 * 5 ** 3)
    chk = _check_mg_replication(m, scfg)
    assert chk.status == "warn" and "truncated" in chk.detail
    scfg = SolverConfig(precond="mg")
    assert _check_mg_replication(m, scfg).status == "ok"
    assert _check_mg_replication(m, SolverConfig()).status == "ok"


def test_mg_default_cutoff_is_active_and_solver_truncation_works():
    """The default cutoff must leave today's models untouched, and a
    solver with a truncating cutoff still converges (shallower cycle)."""
    m = make_cube_model(8, 4, 4, heterogeneous=True)
    cfg = _cfg(precond="mg", mg_max_replicated_dofs=3 * 5 * 3 * 3 + 5)
    s = Solver(m, cfg, mesh=make_mesh(8), n_parts=8)
    assert s._mg_meta["levels"] == 1
    assert s.step(1.0).flag == 0


# ----------------------------------------------------------------------
# analysis: partition-key components rule
# ----------------------------------------------------------------------

def test_partition_key_components_rule_clean_and_seeded():
    from pcg_mpi_solver_tpu.analysis.rules_config import (
        check_partition_key_components)

    assert check_partition_key_components() == []

    # seeded violation: a key that ignores part_idx must fire
    def bad_shard_key(model_fp, *, n_parts, part_idx, backend, dtype,
                      method="n/a", elem_part_hash=None, pad_multiple=8,
                      extra=None):
        if not (0 <= part_idx < n_parts):
            raise KeyError(part_idx)
        return f"{model_fp}:{n_parts}:{backend}:{dtype}:{method}"

    findings = check_partition_key_components(shard_key_fn=bad_shard_key)
    assert any("part_idx" in f.loc for f in findings)

    # seeded violation: out-of-range part_idx silently accepted
    def lax_key(model_fp, **kw):
        from pcg_mpi_solver_tpu.cache.keys import _digest
        return _digest({"kind": "partition-shard", **{
            k: (sorted(v.items()) if isinstance(v, dict) else v)
            for k, v in kw.items()}})

    findings = check_partition_key_components(shard_key_fn=lax_key)
    assert any("part_idx-range" in f.loc for f in findings)


def test_setup_shard_event_schema():
    from pcg_mpi_solver_tpu.obs.schema import validate_event

    ev = {"schema": "pcg-tpu-telemetry/1", "t": 1.0,
          "kind": "setup_shard", "parts": [2, 4], "n_parts": 8,
          "cold": True, "partition_build_s": 0.5}
    assert validate_event(ev) == []
    bad = dict(ev)
    del bad["parts"]
    assert validate_event(bad)


# ----------------------------------------------------------------------
# REAL 4-process warm start: each process reads ONLY its per-part
# entries; solve bit-identical to the monolithic cold build.
# ----------------------------------------------------------------------

_CHILD_WARM = r"""
import json, os, sys
import numpy as np
N_PROCS = int(sys.argv[3]); CACHE = sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={8 // N_PROCS}")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from pcg_mpi_solver_tpu.parallel.distributed import (
    fetch_global, init_distributed, make_global_mesh)
pid = init_distributed(coordinator_address=sys.argv[1],
                       num_processes=N_PROCS, process_id=int(sys.argv[2]))
from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.parallel.partition import BUILD_CALLS
from pcg_mpi_solver_tpu.solver.driver import Solver

class Cap:
    def __init__(self): self.events = []
    def emit(self, ev): self.events.append(ev)
    def close(self): pass

model = make_cube_model(6, 5, 4, heterogeneous=True)
def cfg(**kw):
    return RunConfig(solver=SolverConfig(tol=1e-8, max_iter=500),
                     time_history=TimeHistoryConfig(
                         time_step_delta=[0.0, 1.0], export_flag=False),
                     **kw)
mesh = make_global_mesh()
# reference: the MONOLITHIC cold build on the SAME topology (sharded
# setup off, no cache) — the sharded warm start must match it BITWISE
s_mono = Solver(model, cfg(setup_shard="off"), mesh=mesh, n_parts=8,
                backend="general")
assert s_mono._setup_range is None
r_mono = s_mono.step(1.0)
u_mono = fetch_global(s_mono.un, mesh)

b0 = dict(BUILD_CALLS)
cap = Cap()
s = Solver(model, cfg(cache_dir=CACHE), mesh=mesh, n_parts=8,
           backend="general", recorder=MetricsRecorder(sinks=(cap,)))
assert s.setup_cache == "warm", s.setup_cache
assert BUILD_CALLS == b0, "warm start performed partition work"
rng = s._setup_range
assert rng == (pid * 8 // N_PROCS, (pid + 1) * 8 // N_PROCS), rng
ev = [e for e in cap.events if e.get("kind") == "cache" and e.get("shard")]
assert ev and ev[0]["hit"] and ev[0]["parts"] == list(range(*rng)), ev
sev = [e for e in cap.events if e.get("kind") == "setup_shard"]
assert sev and sev[0]["parts"] == list(rng) and not sev[0]["cold"], sev
r = s.step(1.0)
u = fetch_global(s.un, s.mesh)
assert (r.flag, r.iters) == (r_mono.flag, r_mono.iters), (r, r_mono)
np.testing.assert_array_equal(u, u_mono)       # BIT-identical solve
print("RESULT " + json.dumps({
    "pid": pid, "flag": int(r.flag), "iters": int(r.iters),
    "parts_read": ev[0]["parts"], "entries": int(ev[0]["entries"]),
    "checksum": repr(float(np.abs(u).sum()))}), flush=True)
"""


@pytest.mark.skipif(os.environ.get("PCG_TPU_SKIP_MULTIPROC") == "1",
                    reason="multi-process test disabled")
def test_four_process_warm_start_reads_only_own_shards(tmp_path):
    """ISSUE 14 acceptance: the 4-process warm start reads ONLY each
    process's per-part entries (+ the glue), performs zero partition
    work, and solves BIT-identically to the monolithic cold build on
    the same topology (asserted in-child against a setup_shard='off'
    reference; its iteration count also matches this single-process
    cold build that populated the cache)."""
    model = make_cube_model(6, 5, 4, heterogeneous=True)
    cache = tmp_path / "cache"
    cfg = _cfg(cache)
    s0 = Solver(model, cfg, mesh=make_mesh(8), n_parts=8,
                backend="general")
    assert s0.setup_cache == "cold"
    r0 = s0.step(1.0)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "child.py"
    script.write_text(_CHILD_WARM)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    # file-backed stdout (same pattern as setup_ladder._run_rung): a
    # later child blocking on a full 64KB pipe while the parent drains
    # an earlier child's would wedge the collective group
    logs = [open(tmp_path / f"child{i}.log", "w+") for i in range(4)]
    procs = [subprocess.Popen(
        [sys.executable, str(script), coord, str(i), "4", str(cache)],
        stdout=logs[i], stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(4)]
    outs = []
    try:
        deadline = time.monotonic() + 300
        for p in procs:
            p.wait(timeout=max(1.0, deadline - time.monotonic()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for f in logs:
            f.seek(0)
            outs.append(f.read())
            f.close()
    results = []
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT")]
        results.append(json.loads(line[-1][len("RESULT "):]))
    # each process read ONLY its 2 parts (+ glue), disjointly tiling 0..8
    all_parts = []
    for r in results:
        assert len(r["parts_read"]) == 2 and r["entries"] == 3, r
        all_parts += r["parts_read"]
    assert sorted(all_parts) == list(range(8))
    # every process converged identically (bit-identity vs the
    # monolithic build was asserted IN-CHILD on the same topology;
    # cross-topology reduction order differs, so vs THIS single-process
    # build only the Krylov trajectory length is comparable)
    for r in results:
        assert r["flag"] == 0 and abs(r["iters"] - r0.iters) <= 1, r
        assert r["checksum"] == results[0]["checksum"], results
