"""Telemetry subsystem (obs/): in-graph convergence-trace ring buffer
(clamping, wrap-around, parity against the numpy reference), the metrics
recorder / JSONL event round-trip, and the no-extra-transfer contract."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import JsonlSink, MetricsRecorder, StderrSink
from pcg_mpi_solver_tpu.obs.schema import (
    TELEMETRY_SCHEMA, validate_event, validate_jsonl_text)
from pcg_mpi_solver_tpu.obs.trace import (
    clamp_trace_len, trace_init, trace_record, unpack_trace)
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.driver import Solver
from pcg_mpi_solver_tpu.solver.numpy_ref import NumpyRefSolver


# ---------------------------------------------------------------- ring buffer
def test_clamp_trace_len():
    assert clamp_trace_len(100, 50) == 50      # clamped to max_iter
    assert clamp_trace_len(10, 50) == 10
    assert clamp_trace_len(0, 50) == 1         # floor (callers gate on > 0)
    assert clamp_trace_len(5, 0) == 1


def _record_n(tr, n):
    for i in range(1, n + 1):
        tr = trace_record(
            tr, normr=jnp.asarray(float(i)), rho=jnp.asarray(10.0 * i),
            stag=jnp.asarray(0, jnp.int32), flag=jnp.asarray(1, jnp.int32))
    return tr


def test_trace_no_wrap():
    tr = _record_n(trace_init(8), 5)
    out = unpack_trace(tr)
    assert out.n_recorded == 5 and not out.truncated
    np.testing.assert_allclose(out.normr, [1, 2, 3, 4, 5])
    np.testing.assert_allclose(out.rho, [10, 20, 30, 40, 50])


def test_trace_wrap_around_keeps_last_entries_in_order():
    tr = _record_n(trace_init(4), 7)
    out = unpack_trace(tr)
    assert out.n_recorded == 7 and out.truncated
    # ring holds the LAST 4 records, oldest -> newest
    np.testing.assert_allclose(out.normr, [4, 5, 6, 7])
    np.testing.assert_allclose(out.rho, [40, 50, 60, 70])


def test_trace_scale_restores_absolute_residuals():
    tr = trace_init(2)
    tr = trace_record(tr, normr=jnp.asarray(0.5), rho=jnp.asarray(1.0),
                      stag=jnp.asarray(0, jnp.int32),
                      flag=jnp.asarray(1, jnp.int32),
                      scale=jnp.asarray(8.0))
    out = unpack_trace(tr)
    np.testing.assert_allclose(out.normr, [4.0])


# ------------------------------------------------------------- normr parity
def test_traced_normr_matches_numpy_reference():
    """The in-graph trace must reproduce the host reference's per-iteration
    residual norms — same length, same values (f64 direct mode; both sides
    record the TRUE residual at tol-confirmation iterations)."""
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000, trace_resid=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    r = s.step(1.0)
    assert r.flag == 0
    tr = s.last_trace
    assert tr is not None and not tr.truncated
    assert tr.n_recorded == r.iters
    ref = NumpyRefSolver(model).solve(1.0, tol=1e-8, max_iter=2000)
    assert ref.flag == 0
    assert len(ref.normr_hist) == tr.n_recorded
    # Early iterations: the two f64 implementations are numerically
    # indistinguishable.  Late iterations: the residual RECURRENCES drift
    # apart in low-order bits that compound (different summation orders),
    # so the whole-trace contract is log-space agreement — each recorded
    # norm within a fraction of a decade of the reference's — plus an
    # identical endpoint (both solves land at the same true residual).
    np.testing.assert_allclose(tr.normr[:10], ref.normr_hist[:10],
                               rtol=1e-6)
    np.testing.assert_allclose(np.log10(tr.normr),
                               np.log10(ref.normr_hist), atol=0.5)
    np.testing.assert_allclose(tr.normr[-1], ref.normr_hist[-1], rtol=0.05)
    # the final recorded flag is the termination flag
    assert tr.flag[-1] == 0 and np.all(tr.flag[:-1] == 1)


def test_traced_chunked_identical_to_one_shot():
    """Dispatch chunking must not change the recorded trace (the ring rides
    the resumable carry across dispatch boundaries)."""
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)

    def run(iters_per_dispatch):
        cfg = RunConfig(
            solver=SolverConfig(tol=1e-8, max_iter=2000, trace_resid=2000,
                                iters_per_dispatch=iters_per_dispatch),
            time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
        )
        s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
        s.step(1.0)
        return s.last_trace

    one_shot, chunked = run(0), run(20)
    assert chunked.n_recorded == one_shot.n_recorded
    np.testing.assert_allclose(chunked.normr, one_shot.normr, rtol=1e-12)
    np.testing.assert_array_equal(chunked.flag, one_shot.flag)


def test_traced_mixed_mode_absolute_residuals():
    """Mixed-precision tracing: recorded norms are rescaled to absolute
    residuals, so the trace decays to ~tol*||b|| like the direct trace."""
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=4000, trace_resid=4000,
                            dtype="float32", dot_dtype="float64",
                            precision_mode="mixed"),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    r = s.step(1.0)
    assert r.flag == 0
    tr = s.last_trace
    assert tr.n_recorded == r.iters
    # absolute scale: starts near ||b|| magnitude, ends near tol*||b||
    ref = NumpyRefSolver(model).solve(1.0, tol=1e-8, max_iter=4000)
    n2b = np.linalg.norm(ref.normr_hist[0])
    assert tr.normr[0] > 1e3 * tr.normr[-1]
    assert tr.normr[-1] < 1e-6 * n2b


def test_trace_ring_shorter_than_solve_truncates():
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000, trace_resid=10),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    r = s.step(1.0)
    tr = s.last_trace
    assert tr.truncated and tr.n_recorded == r.iters
    assert len(tr.normr) == 10
    # the retained window is the LAST 10 iterations -> monotone-ish decay
    # into convergence, ending with the termination flag
    assert tr.flag[-1] == 0


# ------------------------------------------------------- recorder + JSONL
def test_recorder_jsonl_round_trip(tmp_path):
    path = str(tmp_path / "t.jsonl")
    rec = MetricsRecorder(sinks=[JsonlSink(path)])
    rec.event("step", step=1, flag=0, relres=1e-9, iters=42, wall_s=0.5)
    rec.note("hello")
    rec.inc("foo", 2)
    rec.gauge("bar", "baz")
    with rec.span("phase1", emit=True):
        pass
    rec.emit_run_summary()
    rec.close()

    text = open(path).read()
    assert validate_jsonl_text(text) == []
    events = [json.loads(ln) for ln in text.splitlines()]
    kinds = [e["kind"] for e in events]
    assert kinds == ["step", "note", "bench_phase", "run_summary"]
    assert all(e["schema"] == TELEMETRY_SCHEMA for e in events)
    step = events[0]
    assert step["iters"] == 42 and step["relres"] == 1e-9
    summary = events[-1]
    assert summary["counters"]["foo"] == 2
    assert summary["gauges"]["bar"] == "baz"
    assert summary["spans"]["phase1"]["calls"] == 1


def test_recorder_jsonl_appends_and_survives_kill(tmp_path):
    """Per-event flush: a half-finished run still leaves parseable lines."""
    path = str(tmp_path / "t.jsonl")
    rec = MetricsRecorder(sinks=[JsonlSink(path)])
    rec.note("one")
    # file is readable BEFORE close (flush-per-event)
    assert validate_jsonl_text(open(path).read()) == []
    rec.close()


def test_validate_event_rejects_missing_fields():
    assert validate_event({"schema": TELEMETRY_SCHEMA, "t": 0.0,
                           "kind": "step", "step": 1}) != []
    assert validate_event({"t": 0.0, "kind": "note", "msg": "x"}) != []
    ok = {"schema": TELEMETRY_SCHEMA, "t": 0.0, "kind": "note", "msg": "x"}
    assert validate_event(ok) == []
    # unknown kinds are forward-compatible (allowed)
    unk = {"schema": TELEMETRY_SCHEMA, "t": 0.0, "kind": "future_thing"}
    assert validate_event(unk) == []


def test_stderr_sink_verbose_alias(capsys, monkeypatch):
    """PCG_TPU_VERBOSE=1 is the alias that turns on the stderr
    breadcrumbs of the default recorder — checked PER EVENT like the
    historical _vlog, so it can be flipped on a live process."""
    monkeypatch.setenv("PCG_TPU_VERBOSE", "1")
    rec = MetricsRecorder.default()
    assert any(isinstance(snk, StderrSink) for snk in rec.sinks)
    rec.note("breadcrumb")
    err = capsys.readouterr().err
    assert "breadcrumb" in err and "[pcg-tpu " in err
    # flipping the env var OFF silences the SAME recorder mid-flight...
    monkeypatch.setenv("PCG_TPU_VERBOSE", "0")
    rec.note("muted")
    assert "muted" not in capsys.readouterr().err
    # ...and back ON re-enables it (the hung-dispatch forensics workflow)
    monkeypatch.setenv("PCG_TPU_VERBOSE", "1")
    rec.note("resumed")
    assert "resumed" in capsys.readouterr().err


def test_solver_step_events_and_dispatch_attribution(tmp_path):
    """Solver wiring end to end: a solve with telemetry_path set writes
    step + resid_trace + run_summary events, and dispatch stats split the
    compile-paying first call from warm calls."""
    path = str(tmp_path / "run.jsonl")
    model = make_cube_model(3, 3, 3)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000, trace_resid=100),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 0.5, 1.0]),
        telemetry_path=path,
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    res = s.solve()
    s.recorder.close()
    assert all(r.flag == 0 for r in res)
    text = open(path).read()
    assert validate_jsonl_text(text) == []
    events = [json.loads(ln) for ln in text.splitlines()]
    steps = [e for e in events if e["kind"] == "step"]
    traces = [e for e in events if e["kind"] == "resid_trace"]
    assert [e["step"] for e in steps] == [1, 2]
    assert len(traces) == 2
    assert traces[0]["n_recorded"] == steps[0]["iters"]
    assert len(traces[0]["normr"]) == min(steps[0]["iters"], 100)
    assert events[-1]["kind"] == "run_summary"
    ds = s.recorder.dispatch_stats()
    assert ds["step"]["calls"] == 2
    # first call paid the XLA compile: cold >> warm on this tiny model
    assert ds["step"]["cold_s"] > ds["step"]["warm_s"]
    gauges = events[-1]["gauges"]
    assert gauges["n_dof"] == model.n_dof
    assert "comm.psums_per_iter" in gauges


def test_cli_telemetry_end_to_end(tmp_path, capsys):
    """The acceptance surface: the CLI demo with --telemetry-out and
    --trace-resid writes schema-valid JSONL with per-step metrics and a
    residual trace matching the host reference within tolerance."""
    from pcg_mpi_solver_tpu.cli import main

    out = str(tmp_path / "out.jsonl")
    main(["demo", "--nx", "4", "--scratch", str(tmp_path / "s"),
          "--tol", "1e-8", "--precision", "direct",
          "--telemetry-out", out, "--trace-resid", "2000", "--summary"])
    stdout = capsys.readouterr().out
    assert ">success!" in stdout
    assert "dispatch" in stdout          # the --summary table
    text = open(out).read()
    assert validate_jsonl_text(text) == []
    events = [json.loads(ln) for ln in text.splitlines()]
    steps = [e for e in events if e["kind"] == "step"]
    traces = [e for e in events if e["kind"] == "resid_trace"]
    assert steps and traces and steps[0]["flag"] == 0
    # the demo model is make_cube_model(nx=4, heterogeneous=True): check
    # the traced residuals against the host-side reference on that model
    from pcg_mpi_solver_tpu.models.synthetic import make_cube_model as mk

    model = mk(4, 0, 0, E=30e9, nu=0.2, load="traction", load_value=1e6,
               heterogeneous=True)
    ref = NumpyRefSolver(model).solve(1.0, tol=1e-8, max_iter=10000)
    tn = np.asarray(traces[0]["normr"])
    assert len(tn) == len(ref.normr_hist)
    np.testing.assert_allclose(np.log10(tn), np.log10(ref.normr_hist),
                               atol=0.5)


def test_tracing_off_no_trace_in_carry():
    """With trace_resid=0 nothing is threaded: no trace output, and the
    carry schema (hence the compiled program) is unchanged."""
    from pcg_mpi_solver_tpu.solver.pcg import carry_part_specs, cold_carry

    model = make_cube_model(3, 3, 3)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    s.step(1.0)
    assert s.last_trace is None and s.trace_len == 0
    import jax

    P, R = (jax.sharding.PartitionSpec("parts"),
            jax.sharding.PartitionSpec())
    assert "trace" not in carry_part_specs(P, R)
    assert "trace" not in cold_carry(jnp.zeros(4), jnp.zeros(4),
                                     jnp.asarray(1.0), jnp.float64)


# ---------------------------------------------------------- flight recorder
#
# ISSUE 12: crash-durable flight records (obs/flight.py), the tolerant
# JSONL ingest every dead-tunnel artifact needs, per-process telemetry
# shards and their merge aggregator, and the SIGKILL-mid-solve
# acceptance path.

import os
import signal
import socket
import subprocess
import sys
import textwrap
import time

from pcg_mpi_solver_tpu.obs.flight import (
    FlightRecorder, find_shards, flight_verdict, flight_verdict_path,
    merge_shards, read_jsonl_tolerant, shard_jsonl_path)


def test_flight_brackets_and_verdicts(tmp_path):
    """begin/end -> clean, a fail bracket -> failed, an unclosed bracket
    -> died; every record is schema-valid and carries BOTH clocks."""
    p = str(tmp_path / "f.jsonl")
    fl = FlightRecorder(p, meta={"component": "test"}, heartbeat_s=30)
    with fl.record("solve:cube", nx=4):
        pass
    fl.close()
    assert validate_jsonl_text(open(p).read()) == []
    events, truncated = read_jsonl_tolerant(p)
    assert truncated == 0
    assert [e["op"] for e in events] == ["meta", "begin", "end"]
    assert all(e["kind"] == "flight" and "mono" in e and "t" in e
               for e in events)
    v = flight_verdict(events)
    assert v["verdict"] == "clean" and v["in_flight"] == []
    assert v["last_wall"] is not None and v["last_mono"] is not None

    # a bracket that raises closes as op=fail and the error survives
    fl2 = FlightRecorder(str(tmp_path / "g.jsonl"), heartbeat_s=30)
    with pytest.raises(RuntimeError):
        with fl2.record("solve:boom"):
            raise RuntimeError("tunnel dropped")
    fl2.close()
    v2 = flight_verdict_path(str(tmp_path / "g.jsonl"))
    assert v2["verdict"] == "failed"
    assert any("tunnel dropped" in f for f in v2["fails"])

    # an unclosed bracket is the kill signature: verdict died
    fl3 = FlightRecorder(str(tmp_path / "h.jsonl"), heartbeat_s=30)
    fl3.begin("dispatch:step")
    fl3.close()
    v3 = flight_verdict_path(str(tmp_path / "h.jsonl"))
    assert v3["verdict"] == "died"
    assert v3["in_flight"] == ["dispatch:step"]

    # a fail bracket stamped expected=True (the bench ladder descending
    # by design) must NOT fail the artifact — and neither must the
    # Solver's unmarked dispatch fail NESTED inside it (the solve raised
    # first; bench only stamps the rung).  A successful descent run
    # reads clean, with the descent still on record.
    fl4 = FlightRecorder(str(tmp_path / "i.jsonl"), heartbeat_s=30)
    seq = fl4.begin("rung:0", nx=160)
    dseq = fl4.begin("dispatch:step")
    fl4.end(dseq, "dispatch:step", ok=False, error="RuntimeError: OOM")
    fl4.end(seq, "rung:0", ok=False, error="RuntimeError: OOM",
            expected=True)
    with fl4.record("rung:1", nx=128):
        pass
    fl4.close()
    v4 = flight_verdict_path(str(tmp_path / "i.jsonl"))
    assert v4["verdict"] == "clean", v4
    assert v4["fails"] == []
    assert [f.split(":")[0] for f in v4["expected_fails"]] == \
        ["dispatch", "rung"]

    # ...but a fail OUTSIDE any expected span still fails the artifact
    fl5 = FlightRecorder(str(tmp_path / "j.jsonl"), heartbeat_s=30)
    seq = fl5.begin("rung:0")
    fl5.end(seq, "rung:0", ok=False, error="OOM", expected=True)
    with pytest.raises(RuntimeError):
        with fl5.record("dispatch:later"):
            raise RuntimeError("real failure")
    fl5.close()
    v5 = flight_verdict_path(str(tmp_path / "j.jsonl"))
    assert v5["verdict"] == "failed", v5
    assert any("real failure" in f for f in v5["fails"])


def test_flight_heartbeats_while_bracket_open(tmp_path):
    """Heartbeats tick only while a bracket is open, carry the in-flight
    names, and stop once the bracket closes."""
    p = str(tmp_path / "hb.jsonl")
    fl = FlightRecorder(p, heartbeat_s=0.06)
    seq = fl.begin("dispatch:long")
    time.sleep(0.4)
    fl.end(seq, "dispatch:long")
    events, _ = read_jsonl_tolerant(p)
    beats = [e for e in events if e["op"] == "heartbeat"]
    assert beats, "no heartbeat while the bracket was open"
    assert all(b["in_flight"] == ["dispatch:long"] for b in beats)
    n = len(beats)
    time.sleep(0.25)
    events, _ = read_jsonl_tolerant(p)
    assert len([e for e in events if e["op"] == "heartbeat"]) == n
    fl.close()


def test_read_jsonl_tolerant_skips_cut_line(tmp_path):
    """The dead-tunnel artifact: a trailing line cut mid-object is
    skipped and counted, never raised on."""
    p = tmp_path / "cut.jsonl"
    good = json.dumps({"schema": TELEMETRY_SCHEMA, "t": 1.0,
                       "kind": "note", "msg": "ok"})
    p.write_text(good + "\n" + good + "\n"
                 + '{"schema": "pcg-tpu-telemetry/1", "kind": "st')
    events, truncated = read_jsonl_tolerant(str(p))
    assert len(events) == 2 and truncated == 1
    # non-object lines count as truncated too, blank lines are ignored
    p.write_text(good + "\n\n[1, 2]\n")
    events, truncated = read_jsonl_tolerant(str(p))
    assert len(events) == 1 and truncated == 1


def test_solver_flight_path_brackets_every_dispatch(tmp_path):
    """RunConfig.flight_path wires the recorder through the Solver: a
    dead previous run's artifact at the same path is rotated to .prev
    (never appended to — reused seq numbers would close its unclosed
    brackets), the solve dispatch lands between fsync'd begin/end flight
    records, and a completed run reads verdict=clean."""
    p = str(tmp_path / "solve_flight.jsonl")
    stale = FlightRecorder(p, heartbeat_s=30)
    stale.begin("dispatch:killed previous run")     # never closed
    stale.close()
    model = make_cube_model(4, 0, 0, E=30e9, nu=0.2, load="traction",
                            load_value=1e6, heterogeneous=True)
    cfg = RunConfig(flight_path=p,
                    solver=SolverConfig(tol=1e-8, max_iter=2000))
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    r = s.step(1.0)
    assert r.flag == 0
    s.recorder.close()
    prev = flight_verdict_path(p + ".prev")
    assert prev["verdict"] == "died"
    assert prev["in_flight"] == ["dispatch:killed previous run"]
    assert validate_jsonl_text(open(p).read()) == []
    events, truncated = read_jsonl_tolerant(p)
    assert truncated == 0
    begins = [e["name"] for e in events if e["op"] == "begin"]
    assert "dispatch:step" in begins
    v = flight_verdict(events)
    assert v["verdict"] == "clean", v


def test_flight_attach_survives_typod_heartbeat_env(tmp_path, monkeypatch):
    """A typo'd PCG_TPU_FLIGHT_HEARTBEAT_S must not cost the run:
    FlightRecorder falls back to the default cadence and attach_flight
    still wires up (its contract says observability never aborts the
    solve it observes)."""
    from pcg_mpi_solver_tpu.obs.flight import attach_flight

    monkeypatch.setenv("PCG_TPU_FLIGHT_HEARTBEAT_S", "5s")
    rec = MetricsRecorder()
    fl = attach_flight(rec, str(tmp_path / "f.jsonl"), "test")
    assert fl is not None and fl.heartbeat_s == 5.0
    fl.close()


def test_ingest_rotation_failure_diverts_to_fallback_path(
        tmp_path, monkeypatch):
    """When the leftover artifact can't be rotated (read-only dir, NFS
    hiccup) the new stream must NOT append to it — the fresh recorder's
    reused seq numbers would close the dead run's brackets and its
    'died' verdict would read clean.  ingest_and_rotate diverts the new
    stream to a unique .<pid> sibling instead."""
    import pcg_mpi_solver_tpu.obs.flight as flight_mod

    p = str(tmp_path / "wedged.jsonl")
    stale = FlightRecorder(p, heartbeat_s=30)
    stale.begin("dispatch:killed previous run")     # never closed
    stale.close()

    def deny_replace(src, dst):
        raise OSError("read-only directory")

    monkeypatch.setattr(flight_mod.os, "replace", deny_replace)
    notes = []
    safe = flight_mod.ingest_and_rotate(p, notes.append)
    assert safe == f"{p}.{os.getpid()}"
    assert any("could not be read/rotated" in m for m in notes), notes

    # the shared attach wiring uses the diverted path end-to-end
    rec = MetricsRecorder()
    fl = flight_mod.attach_flight(rec, p, "test")
    assert fl is not None and fl.path == safe
    with fl.record("dispatch:fresh"):
        pass
    fl.close()
    # the dead run's artifact is untouched and still reads died
    v_old = flight_verdict_path(p)
    assert v_old["verdict"] == "died"
    assert v_old["in_flight"] == ["dispatch:killed previous run"]
    assert flight_verdict_path(safe)["verdict"] == "clean"


def test_dynamics_driver_flight_path_wires_brackets(tmp_path):
    """--flight-out / RunConfig.flight_path must not be a silent no-op
    for the explicit-dynamics driver: its chunk dispatches land between
    flight brackets exactly like the quasi-static Solver's (a long time
    history is the run a tunnel death orphans)."""
    from pcg_mpi_solver_tpu.solver.dynamics import DynamicsSolver, stable_dt

    p = str(tmp_path / "dyn_flight.jsonl")
    model = make_cube_model(3, 3, 3, E=100.0, nu=0.25, rho=1.0,
                            load="traction", load_value=1.0)
    dyn = DynamicsSolver(model, RunConfig(flight_path=p),
                         mesh=make_mesh(1), n_parts=1,
                         dt=stable_dt(model, safety=0.5))
    dyn.run(n_steps=3)
    dyn.recorder.close()
    events, _ = read_jsonl_tolerant(p)
    begins = [e["name"] for e in events if e["op"] == "begin"]
    assert any(n.startswith("dispatch:") for n in begins), begins
    assert flight_verdict(events)["verdict"] == "clean"


def test_dispatch_failure_records_error_text(tmp_path):
    """A dispatch that raises must close its flight bracket with the
    exception text — `pcg-tpu summary` on the crash artifact prints the
    actual error, not 'dispatch:step: ?'."""
    p = str(tmp_path / "boom.jsonl")
    rec = MetricsRecorder(sinks=[])
    rec.flight = FlightRecorder(p, heartbeat_s=30)
    with pytest.raises(RuntimeError):
        with rec.dispatch("step"):
            raise RuntimeError("UNAVAILABLE: tunnel dropped")
    rec.close()
    v = flight_verdict_path(p)
    assert v["verdict"] == "failed"
    assert any("UNAVAILABLE: tunnel dropped" in f for f in v["fails"]), v


def test_flight_write_trouble_never_raises(tmp_path):
    """Disk trouble mid-run (handle gone, disk full) must never cost the
    run: emit swallows the write error and the brackets keep working."""
    p = str(tmp_path / "trouble.jsonl")
    fl = FlightRecorder(p, heartbeat_s=30)
    with fl.record("dispatch:ok"):
        pass
    fl._f.close()                   # simulate the handle dying mid-run
    with fl.record("dispatch:unrecorded"):
        pass                        # must not raise
    fl.close()
    v = flight_verdict_path(p)
    assert v["verdict"] == "clean"  # the pre-trouble records survive


_KILL_CHILD = textwrap.dedent("""\
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig
    from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver.driver import Solver

    model = make_cube_model(10, 0, 0, E=30e9, nu=0.2, load="traction",
                            load_value=1e6, heterogeneous=True)
    cfg = RunConfig(flight_path=sys.argv[1],
                    solver=SolverConfig(tol=1e-30, max_iter=200000))
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    while True:                     # killed from outside mid-dispatch
        s.step(1.0)
        s.reset_state()
""")


def test_sigkill_mid_solve_leaves_parseable_flight_record(tmp_path,
                                                          capsys):
    """The acceptance path: SIGKILL a solve mid-dispatch; the flight
    JSONL on disk must read verdict=died with the in-flight dispatch
    named, `pcg-tpu summary` must parse it without error, and the bench
    salvage/startup path must ingest + rotate it mechanically."""
    p = str(tmp_path / "killed.jsonl")
    script = tmp_path / "child.py"
    script.write_text(_KILL_CHILD)
    env = dict(os.environ)
    env["PCG_TPU_FLIGHT_HEARTBEAT_S"] = "0.2"
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    proc = subprocess.Popen([sys.executable, str(script), p],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL, env=env)
    try:
        deadline = time.time() + 180
        while time.time() < deadline:
            if os.path.exists(p) and \
                    flight_verdict_path(p)["in_flight"]:
                break
            assert proc.poll() is None, "child exited before the kill"
            time.sleep(0.05)
        else:
            raise AssertionError("no in-flight bracket before timeout")
        time.sleep(0.5)             # let a heartbeat land mid-flight
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    v = flight_verdict_path(p)
    assert v["verdict"] == "died", v
    assert any(n.startswith("dispatch:") for n in v["in_flight"]), v
    assert v["last_mono"] is not None and v["last_wall"] is not None
    events, _ = read_jsonl_tolerant(p)
    assert any(e["op"] == "heartbeat" for e in events)

    # `pcg-tpu summary` parses the artifact without error
    from pcg_mpi_solver_tpu.cli import main

    main(["summary", p])
    out = capsys.readouterr().out
    assert "flight verdict: died" in out
    assert "in flight at death: dispatch:" in out

    # the bench startup/salvage path ingests the SAME artifact
    # mechanically: verdict logged, file rotated to .prev, fresh
    # recorder armed in its place
    from pcg_mpi_solver_tpu import bench

    bench_path = str(tmp_path / "bench_flight.jsonl")
    os.rename(p, bench_path)
    old_env = os.environ.get("BENCH_FLIGHT")
    os.environ["BENCH_FLIGHT"] = bench_path
    try:
        fl = bench._attach_flight()
        assert fl is not None
        fl.close()
    finally:
        bench._REC.flight = None
        if old_env is None:
            os.environ.pop("BENCH_FLIGHT", None)
        else:
            os.environ["BENCH_FLIGHT"] = old_env
    err = capsys.readouterr().err
    assert "verdict=died" in err
    assert os.path.exists(bench_path + ".prev")
    v_new = flight_verdict_path(bench_path)
    assert v_new["verdict"] == "clean"      # the fresh stream: meta only


def test_summary_cli_tolerates_truncated_artifact(tmp_path, capsys):
    """`pcg-tpu summary` on the exact artifact a dead tunnel produces:
    the cut trailing line is skipped and REPORTED, the intact events
    still build the tables."""
    from pcg_mpi_solver_tpu.cli import main

    p = tmp_path / "run.jsonl"
    lines = [
        json.dumps({"schema": TELEMETRY_SCHEMA, "t": 1.0, "kind": "step",
                    "step": 1, "flag": 0, "relres": 1e-9, "iters": 42,
                    "wall_s": 0.5}),
        json.dumps({"schema": TELEMETRY_SCHEMA, "t": 2.0,
                    "kind": "dispatch", "name": "step", "wall_s": 0.4,
                    "cold": True}),
    ]
    p.write_text("\n".join(lines)
                 + '\n{"schema": "pcg-tpu-telemetry/1", "kind": "ste')
    main(["summary", str(p)])
    out = capsys.readouterr().out
    assert "truncated_lines = 1" in out
    assert "42" in out                      # the step table survived
    assert "partial write of a killed process" in out
    with pytest.raises(SystemExit):
        main(["summary", str(tmp_path / "no_such.jsonl")])


def test_summary_cli_falls_back_to_shards(tmp_path, capsys):
    """A multi-process run shards run.jsonl away to run.p<idx>.jsonl;
    `pcg-tpu summary run.jsonl` (the documented invocation) must find
    and summarize the shards instead of hard-failing on the base name."""
    from pcg_mpi_solver_tpu.cli import main

    def ev(t, step):
        return json.dumps({"schema": TELEMETRY_SCHEMA, "t": t,
                           "kind": "step", "step": step, "flag": 0,
                           "relres": 1e-9, "iters": 7, "wall_s": 0.1})

    (tmp_path / "run.p0.jsonl").write_text(ev(1.0, 1) + "\n")
    (tmp_path / "run.p1.jsonl").write_text(ev(2.0, 2) + "\n")
    main(["summary", str(tmp_path / "run.jsonl")])
    out = capsys.readouterr().out
    assert "2 per-process shard(s)" in out
    assert "run.p0.jsonl" in out and "run.p1.jsonl" in out


def test_shard_path_and_find_shards(tmp_path):
    base = str(tmp_path / "run.jsonl")
    # single-process: the path is untouched (existing workflows keep
    # their exact filenames)
    assert shard_jsonl_path(base, 0, 1) == base
    assert shard_jsonl_path(base, 3, 4) == str(tmp_path / "run.p3.jsonl")
    for name in ("run.jsonl", "run.p0.jsonl", "run.p1.jsonl",
                 "run.p10.jsonl", "run.pX.jsonl", "other.p0.jsonl"):
        (tmp_path / name).write_text("")
    shards = find_shards(base)
    assert shards == [base, str(tmp_path / "run.p0.jsonl"),
                      str(tmp_path / "run.p1.jsonl"),
                      str(tmp_path / "run.p10.jsonl")]
    # an extension-less base path: shard_jsonl_path falls back to
    # .jsonl, so discovery must apply the SAME fallback
    bare = str(tmp_path / "bare")
    assert shard_jsonl_path(bare, 3, 4) == str(tmp_path / "bare.p3.jsonl")
    (tmp_path / "bare.p3.jsonl").write_text("")
    assert find_shards(bare) == [str(tmp_path / "bare.p3.jsonl")]


def test_merge_shards_time_orders_and_tags(tmp_path):
    """The aggregator: per-process shards merge into one time-ordered
    stream, every event tagged with its source shard, truncated lines
    skipped and counted per shard."""

    def ev(t, msg):
        return json.dumps({"schema": TELEMETRY_SCHEMA, "t": t,
                           "kind": "note", "msg": msg})

    p0 = tmp_path / "run.p0.jsonl"
    p1 = tmp_path / "run.p1.jsonl"
    p0.write_text(ev(1.0, "a") + "\n" + ev(3.0, "c") + "\n")
    p1.write_text(ev(2.0, "b") + "\n" + ev(4.0, "d") + "\n"
                  + '{"cut": ')
    out = str(tmp_path / "merged.jsonl")
    stats = merge_shards([str(p0), str(p1)], out)
    assert stats["events"] == 4 and stats["truncated_lines"] == 1
    assert stats["shards"]["run.p1.jsonl"]["truncated"] == 1
    merged = [json.loads(ln) for ln in open(out)]
    assert [e["msg"] for e in merged] == ["a", "b", "c", "d"]
    assert [e["shard"] for e in merged] == [
        "run.p0.jsonl", "run.p1.jsonl", "run.p0.jsonl", "run.p1.jsonl"]
    assert validate_jsonl_text(open(out).read()) == []


def test_merged_flight_verdict_pairs_brackets_per_shard(tmp_path):
    """Per-shard seq counters all start at 1, so a merged stream must
    pair begin/end PER SOURCE SHARD — process 1's end must not close
    process 0's unclosed begin (a died shard would read clean)."""
    f0 = FlightRecorder(str(tmp_path / "fl.p0.jsonl"), heartbeat_s=30)
    f0.begin("dispatch:p0-died-here")           # never closed
    f0.close()
    f1 = FlightRecorder(str(tmp_path / "fl.p1.jsonl"), heartbeat_s=30)
    with f1.record("dispatch:p1-fine"):         # same seq as p0's begin
        pass
    f1.close()
    out = str(tmp_path / "merged.jsonl")
    merge_shards([str(tmp_path / "fl.p0.jsonl"),
                  str(tmp_path / "fl.p1.jsonl")], out)
    v = flight_verdict_path(out)
    assert v["verdict"] == "died", v
    assert v["in_flight"] == ["dispatch:p0-died-here"]


def test_merge_shards_disambiguates_same_basename(tmp_path):
    """Cross-directory twins (two per-host collection dirs both holding
    run.p0.jsonl) must NOT collapse: stats keyed per input, and one
    run's end (same seq) must not close the other run's death."""
    da, db = tmp_path / "hostA", tmp_path / "hostB"
    fa = FlightRecorder(str(da / "run.p0.jsonl"), heartbeat_s=30)
    fa.begin("dispatch:hostA-died-here")        # never closed
    fa.close()
    fb = FlightRecorder(str(db / "run.p0.jsonl"), heartbeat_s=30)
    with fb.record("dispatch:hostB-fine"):      # same basename, same seq
        pass
    fb.close()
    out = str(tmp_path / "merged.jsonl")
    pa, pb = str(da / "run.p0.jsonl"), str(db / "run.p0.jsonl")
    stats = merge_shards([pa, pb], out)
    assert set(stats["shards"]) == {pa, pb}     # full paths, not basenames
    merged = [json.loads(ln) for ln in open(out)]
    assert {e["shard"] for e in merged} == {pa, pb}
    v = flight_verdict_path(out)
    assert v["verdict"] == "died", v
    assert v["in_flight"] == ["dispatch:hostA-died-here"]
    # the same file listed twice still yields two distinct stat keys
    stats2 = merge_shards([pa, pa], out)
    assert len(stats2["shards"]) == 2 and stats2["events"] > 0


def test_telemetry_merge_cli(tmp_path, capsys):
    from pcg_mpi_solver_tpu.cli import main

    base = tmp_path / "run.jsonl"
    ev = json.dumps({"schema": TELEMETRY_SCHEMA, "t": 1.0,
                     "kind": "note", "msg": "x"})
    base.write_text(ev + "\n")
    (tmp_path / "run.p1.jsonl").write_text(ev + "\n" + ev + "\n")
    out = str(tmp_path / "merged.jsonl")
    main(["telemetry-merge", str(base), "--out", out])
    stdout = capsys.readouterr().out
    assert ">merged 3 event(s) from 2 shard(s)" in stdout
    assert len(open(out).read().splitlines()) == 3
    with pytest.raises(SystemExit):
        main(["telemetry-merge", str(tmp_path / "ghost.jsonl"),
              "--out", out])


_SHARD_CHILD = textwrap.dedent("""\
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from pcg_mpi_solver_tpu.parallel.distributed import init_distributed

    pid = init_distributed(coordinator_address=sys.argv[1],
                           num_processes=2, process_id=int(sys.argv[2]))
    assert jax.process_count() == 2

    from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder

    rec = MetricsRecorder.default(jsonl_path=sys.argv[3])
    rec.note(f"hello from process {pid}")
    rec.close()
    print(f"RESULT {pid} ok", flush=True)
""")


@pytest.mark.skipif(os.environ.get("PCG_TPU_SKIP_MULTIPROC") == "1",
                    reason="multi-process test disabled")
def test_two_process_telemetry_shards_merge_round_trip(tmp_path, capsys):
    """Under 2-process jax.distributed every process writes its OWN
    telemetry shard (run.p<idx>.jsonl — interleaved appends to one file
    would corrupt it) and `pcg-tpu telemetry-merge` reassembles one
    attributed stream.  No collective compute: sharding must work even
    where multi-process CPU computations don't."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "child.py"
    script.write_text(_SHARD_CHILD)
    base = str(tmp_path / "run.jsonl")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    procs = [subprocess.Popen(
                 [sys.executable, str(script), coord, str(i), base],
                 stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                 text=True, env=env)
             for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"

    # each process wrote its own shard; the unsharded base was NOT used
    assert not os.path.exists(base)
    assert os.path.exists(str(tmp_path / "run.p0.jsonl"))
    assert os.path.exists(str(tmp_path / "run.p1.jsonl"))

    from pcg_mpi_solver_tpu.cli import main

    merged = str(tmp_path / "merged.jsonl")
    main(["telemetry-merge", base, "--out", merged])
    capsys.readouterr()
    events = [json.loads(ln) for ln in open(merged)]
    notes = [e for e in events if e["kind"] == "note"]
    assert {n["msg"] for n in notes} == {"hello from process 0",
                                         "hello from process 1"}
    assert {n["shard"] for n in notes} == {"run.p0.jsonl",
                                           "run.p1.jsonl"}
