"""Telemetry subsystem (obs/): in-graph convergence-trace ring buffer
(clamping, wrap-around, parity against the numpy reference), the metrics
recorder / JSONL event round-trip, and the no-extra-transfer contract."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import JsonlSink, MetricsRecorder, StderrSink
from pcg_mpi_solver_tpu.obs.schema import (
    TELEMETRY_SCHEMA, validate_event, validate_jsonl_text)
from pcg_mpi_solver_tpu.obs.trace import (
    clamp_trace_len, trace_init, trace_record, unpack_trace)
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.driver import Solver
from pcg_mpi_solver_tpu.solver.numpy_ref import NumpyRefSolver


# ---------------------------------------------------------------- ring buffer
def test_clamp_trace_len():
    assert clamp_trace_len(100, 50) == 50      # clamped to max_iter
    assert clamp_trace_len(10, 50) == 10
    assert clamp_trace_len(0, 50) == 1         # floor (callers gate on > 0)
    assert clamp_trace_len(5, 0) == 1


def _record_n(tr, n):
    for i in range(1, n + 1):
        tr = trace_record(
            tr, normr=jnp.asarray(float(i)), rho=jnp.asarray(10.0 * i),
            stag=jnp.asarray(0, jnp.int32), flag=jnp.asarray(1, jnp.int32))
    return tr


def test_trace_no_wrap():
    tr = _record_n(trace_init(8), 5)
    out = unpack_trace(tr)
    assert out.n_recorded == 5 and not out.truncated
    np.testing.assert_allclose(out.normr, [1, 2, 3, 4, 5])
    np.testing.assert_allclose(out.rho, [10, 20, 30, 40, 50])


def test_trace_wrap_around_keeps_last_entries_in_order():
    tr = _record_n(trace_init(4), 7)
    out = unpack_trace(tr)
    assert out.n_recorded == 7 and out.truncated
    # ring holds the LAST 4 records, oldest -> newest
    np.testing.assert_allclose(out.normr, [4, 5, 6, 7])
    np.testing.assert_allclose(out.rho, [40, 50, 60, 70])


def test_trace_scale_restores_absolute_residuals():
    tr = trace_init(2)
    tr = trace_record(tr, normr=jnp.asarray(0.5), rho=jnp.asarray(1.0),
                      stag=jnp.asarray(0, jnp.int32),
                      flag=jnp.asarray(1, jnp.int32),
                      scale=jnp.asarray(8.0))
    out = unpack_trace(tr)
    np.testing.assert_allclose(out.normr, [4.0])


# ------------------------------------------------------------- normr parity
def test_traced_normr_matches_numpy_reference():
    """The in-graph trace must reproduce the host reference's per-iteration
    residual norms — same length, same values (f64 direct mode; both sides
    record the TRUE residual at tol-confirmation iterations)."""
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000, trace_resid=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    r = s.step(1.0)
    assert r.flag == 0
    tr = s.last_trace
    assert tr is not None and not tr.truncated
    assert tr.n_recorded == r.iters
    ref = NumpyRefSolver(model).solve(1.0, tol=1e-8, max_iter=2000)
    assert ref.flag == 0
    assert len(ref.normr_hist) == tr.n_recorded
    # Early iterations: the two f64 implementations are numerically
    # indistinguishable.  Late iterations: the residual RECURRENCES drift
    # apart in low-order bits that compound (different summation orders),
    # so the whole-trace contract is log-space agreement — each recorded
    # norm within a fraction of a decade of the reference's — plus an
    # identical endpoint (both solves land at the same true residual).
    np.testing.assert_allclose(tr.normr[:10], ref.normr_hist[:10],
                               rtol=1e-6)
    np.testing.assert_allclose(np.log10(tr.normr),
                               np.log10(ref.normr_hist), atol=0.5)
    np.testing.assert_allclose(tr.normr[-1], ref.normr_hist[-1], rtol=0.05)
    # the final recorded flag is the termination flag
    assert tr.flag[-1] == 0 and np.all(tr.flag[:-1] == 1)


def test_traced_chunked_identical_to_one_shot():
    """Dispatch chunking must not change the recorded trace (the ring rides
    the resumable carry across dispatch boundaries)."""
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)

    def run(iters_per_dispatch):
        cfg = RunConfig(
            solver=SolverConfig(tol=1e-8, max_iter=2000, trace_resid=2000,
                                iters_per_dispatch=iters_per_dispatch),
            time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
        )
        s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
        s.step(1.0)
        return s.last_trace

    one_shot, chunked = run(0), run(20)
    assert chunked.n_recorded == one_shot.n_recorded
    np.testing.assert_allclose(chunked.normr, one_shot.normr, rtol=1e-12)
    np.testing.assert_array_equal(chunked.flag, one_shot.flag)


def test_traced_mixed_mode_absolute_residuals():
    """Mixed-precision tracing: recorded norms are rescaled to absolute
    residuals, so the trace decays to ~tol*||b|| like the direct trace."""
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=4000, trace_resid=4000,
                            dtype="float32", dot_dtype="float64",
                            precision_mode="mixed"),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    r = s.step(1.0)
    assert r.flag == 0
    tr = s.last_trace
    assert tr.n_recorded == r.iters
    # absolute scale: starts near ||b|| magnitude, ends near tol*||b||
    ref = NumpyRefSolver(model).solve(1.0, tol=1e-8, max_iter=4000)
    n2b = np.linalg.norm(ref.normr_hist[0])
    assert tr.normr[0] > 1e3 * tr.normr[-1]
    assert tr.normr[-1] < 1e-6 * n2b


def test_trace_ring_shorter_than_solve_truncates():
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000, trace_resid=10),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    r = s.step(1.0)
    tr = s.last_trace
    assert tr.truncated and tr.n_recorded == r.iters
    assert len(tr.normr) == 10
    # the retained window is the LAST 10 iterations -> monotone-ish decay
    # into convergence, ending with the termination flag
    assert tr.flag[-1] == 0


# ------------------------------------------------------- recorder + JSONL
def test_recorder_jsonl_round_trip(tmp_path):
    path = str(tmp_path / "t.jsonl")
    rec = MetricsRecorder(sinks=[JsonlSink(path)])
    rec.event("step", step=1, flag=0, relres=1e-9, iters=42, wall_s=0.5)
    rec.note("hello")
    rec.inc("foo", 2)
    rec.gauge("bar", "baz")
    with rec.span("phase1", emit=True):
        pass
    rec.emit_run_summary()
    rec.close()

    text = open(path).read()
    assert validate_jsonl_text(text) == []
    events = [json.loads(ln) for ln in text.splitlines()]
    kinds = [e["kind"] for e in events]
    assert kinds == ["step", "note", "bench_phase", "run_summary"]
    assert all(e["schema"] == TELEMETRY_SCHEMA for e in events)
    step = events[0]
    assert step["iters"] == 42 and step["relres"] == 1e-9
    summary = events[-1]
    assert summary["counters"]["foo"] == 2
    assert summary["gauges"]["bar"] == "baz"
    assert summary["spans"]["phase1"]["calls"] == 1


def test_recorder_jsonl_appends_and_survives_kill(tmp_path):
    """Per-event flush: a half-finished run still leaves parseable lines."""
    path = str(tmp_path / "t.jsonl")
    rec = MetricsRecorder(sinks=[JsonlSink(path)])
    rec.note("one")
    # file is readable BEFORE close (flush-per-event)
    assert validate_jsonl_text(open(path).read()) == []
    rec.close()


def test_validate_event_rejects_missing_fields():
    assert validate_event({"schema": TELEMETRY_SCHEMA, "t": 0.0,
                           "kind": "step", "step": 1}) != []
    assert validate_event({"t": 0.0, "kind": "note", "msg": "x"}) != []
    ok = {"schema": TELEMETRY_SCHEMA, "t": 0.0, "kind": "note", "msg": "x"}
    assert validate_event(ok) == []
    # unknown kinds are forward-compatible (allowed)
    unk = {"schema": TELEMETRY_SCHEMA, "t": 0.0, "kind": "future_thing"}
    assert validate_event(unk) == []


def test_stderr_sink_verbose_alias(capsys, monkeypatch):
    """PCG_TPU_VERBOSE=1 is the alias that turns on the stderr
    breadcrumbs of the default recorder — checked PER EVENT like the
    historical _vlog, so it can be flipped on a live process."""
    monkeypatch.setenv("PCG_TPU_VERBOSE", "1")
    rec = MetricsRecorder.default()
    assert any(isinstance(snk, StderrSink) for snk in rec.sinks)
    rec.note("breadcrumb")
    err = capsys.readouterr().err
    assert "breadcrumb" in err and "[pcg-tpu " in err
    # flipping the env var OFF silences the SAME recorder mid-flight...
    monkeypatch.setenv("PCG_TPU_VERBOSE", "0")
    rec.note("muted")
    assert "muted" not in capsys.readouterr().err
    # ...and back ON re-enables it (the hung-dispatch forensics workflow)
    monkeypatch.setenv("PCG_TPU_VERBOSE", "1")
    rec.note("resumed")
    assert "resumed" in capsys.readouterr().err


def test_solver_step_events_and_dispatch_attribution(tmp_path):
    """Solver wiring end to end: a solve with telemetry_path set writes
    step + resid_trace + run_summary events, and dispatch stats split the
    compile-paying first call from warm calls."""
    path = str(tmp_path / "run.jsonl")
    model = make_cube_model(3, 3, 3)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000, trace_resid=100),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 0.5, 1.0]),
        telemetry_path=path,
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    res = s.solve()
    s.recorder.close()
    assert all(r.flag == 0 for r in res)
    text = open(path).read()
    assert validate_jsonl_text(text) == []
    events = [json.loads(ln) for ln in text.splitlines()]
    steps = [e for e in events if e["kind"] == "step"]
    traces = [e for e in events if e["kind"] == "resid_trace"]
    assert [e["step"] for e in steps] == [1, 2]
    assert len(traces) == 2
    assert traces[0]["n_recorded"] == steps[0]["iters"]
    assert len(traces[0]["normr"]) == min(steps[0]["iters"], 100)
    assert events[-1]["kind"] == "run_summary"
    ds = s.recorder.dispatch_stats()
    assert ds["step"]["calls"] == 2
    # first call paid the XLA compile: cold >> warm on this tiny model
    assert ds["step"]["cold_s"] > ds["step"]["warm_s"]
    gauges = events[-1]["gauges"]
    assert gauges["n_dof"] == model.n_dof
    assert "comm.psums_per_iter" in gauges


def test_cli_telemetry_end_to_end(tmp_path, capsys):
    """The acceptance surface: the CLI demo with --telemetry-out and
    --trace-resid writes schema-valid JSONL with per-step metrics and a
    residual trace matching the host reference within tolerance."""
    from pcg_mpi_solver_tpu.cli import main

    out = str(tmp_path / "out.jsonl")
    main(["demo", "--nx", "4", "--scratch", str(tmp_path / "s"),
          "--tol", "1e-8", "--precision", "direct",
          "--telemetry-out", out, "--trace-resid", "2000", "--summary"])
    stdout = capsys.readouterr().out
    assert ">success!" in stdout
    assert "dispatch" in stdout          # the --summary table
    text = open(out).read()
    assert validate_jsonl_text(text) == []
    events = [json.loads(ln) for ln in text.splitlines()]
    steps = [e for e in events if e["kind"] == "step"]
    traces = [e for e in events if e["kind"] == "resid_trace"]
    assert steps and traces and steps[0]["flag"] == 0
    # the demo model is make_cube_model(nx=4, heterogeneous=True): check
    # the traced residuals against the host-side reference on that model
    from pcg_mpi_solver_tpu.models.synthetic import make_cube_model as mk

    model = mk(4, 0, 0, E=30e9, nu=0.2, load="traction", load_value=1e6,
               heterogeneous=True)
    ref = NumpyRefSolver(model).solve(1.0, tol=1e-8, max_iter=10000)
    tn = np.asarray(traces[0]["normr"])
    assert len(tn) == len(ref.normr_hist)
    np.testing.assert_allclose(np.log10(tn), np.log10(ref.normr_hist),
                               atol=0.5)


def test_tracing_off_no_trace_in_carry():
    """With trace_resid=0 nothing is threaded: no trace output, and the
    carry schema (hence the compiled program) is unchanged."""
    from pcg_mpi_solver_tpu.solver.pcg import carry_part_specs, cold_carry

    model = make_cube_model(3, 3, 3)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    s.step(1.0)
    assert s.last_trace is None and s.trace_len == 0
    import jax

    P, R = (jax.sharding.PartitionSpec("parts"),
            jax.sharding.PartitionSpec())
    assert "trace" not in carry_part_specs(P, R)
    assert "trace" not in cold_carry(jnp.zeros(4), jnp.zeros(4),
                                     jnp.asarray(1.0), jnp.float64)
