"""Time-history resilience (ISSUE 4): the recovery stack wired into the
dynamics/Newmark drivers — timestep-granular snapshots
(resilience/engine.TimeHistoryGuard + utils/checkpoint.SnapshotStore
``step_*.npz``), kill-and-resume bit-identity MID-TIME-HISTORY, NaN/Inf
rollback instead of silently integrating garbage, the per-step PCG
breakdown ladder for Newmark, step-domain fault injection
(``mode@s:N``), and the on-disk retention bound (PCG_TPU_SNAP_KEEP)."""

import glob
import os

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.resilience import FaultPlan, SimulatedKill
from pcg_mpi_solver_tpu.solver.dynamics import DynamicsSolver, stable_dt
from pcg_mpi_solver_tpu.solver.newmark import NewmarkSolver


class _Capture:
    def __init__(self):
        self.events = []

    def emit(self, ev):
        self.events.append(ev)

    def close(self):
        pass


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("PCG_TPU_RETRY_BACKOFF_S", "0.01")


@pytest.fixture(scope="module")
def model():
    return make_cube_model(4, 3, 3, heterogeneous=True)


@pytest.fixture(scope="module")
def dyn_model():
    return make_cube_model(4, 3, 3, E=100.0, nu=0.25, rho=1.0,
                           load="traction", load_value=1.0,
                           heterogeneous=True)


DELTAS = [0.5, 1.0, 1.0, 0.7, 0.3]


def _ncfg(tmp_path, run_id, ipd=0, snap=0, trace=0, **kw):
    kw.setdefault("tol", 1e-10)
    cfg = RunConfig(
        scratch_path=str(tmp_path), run_id=run_id,
        solver=SolverConfig(max_iter=2000, iters_per_dispatch=ipd,
                            trace_resid=trace, **kw))
    cfg.snapshot_every = snap
    return cfg


# ----------------------------------------------------------------------
# Step-domain fault plan
# ----------------------------------------------------------------------

def test_step_domain_parse_and_fire():
    import jax.numpy as jnp

    p = FaultPlan("kill@s:3, nan@s:5, exc@2")
    assert p.armed and p.step_armed
    assert p.next_step_fault(0) == 3
    assert p.next_step_fault(3) == 5
    assert p.next_step_fault(5) is None
    state = {"u": jnp.asarray([1.0, 2.0]), "v": jnp.asarray([0.0, 1.0])}
    clean = p.at_step(1, dict(state))               # nothing fires at 1
    assert np.isfinite(np.asarray(clean["u"])).all()
    out = p.at_step(5, dict(state))
    assert np.isnan(np.asarray(out["u"])).all()
    np.testing.assert_array_equal(np.asarray(out["v"]),
                                  np.asarray(state["v"]))
    with pytest.raises(SimulatedKill):
        p.at_step(3, dict(state))
    # absolute indexing: a consumed step fault never re-fires
    out2 = p.at_step(5, dict(state))
    assert np.isfinite(np.asarray(out2["u"])).all()

    # modes without a step-domain trigger are rejected at parse
    with pytest.raises(ValueError, match="step-domain"):
        FaultPlan("exc@s:1")
    with pytest.raises(ValueError, match="bad fault term"):
        FaultPlan("kill@s:")


# ----------------------------------------------------------------------
# SnapshotStore: step prefix, latest(), retention bound
# ----------------------------------------------------------------------

def test_snapshot_retention_bound(tmp_path, monkeypatch):
    from pcg_mpi_solver_tpu.utils.checkpoint import SnapshotStore

    store = SnapshotStore(str(tmp_path), {"v": 1}, prefix="step")
    for t in range(1, 7):
        store.save(t, {"u": np.full(3, float(t))})
    files = sorted(os.path.basename(p) for p in
                   glob.glob(str(tmp_path / "step_*.npz")))
    assert files == ["step_000005.npz", "step_000006.npz"]   # default K=2
    assert store.latest() == 6

    monkeypatch.setenv("PCG_TPU_SNAP_KEEP", "4")
    for t in range(7, 10):
        store.save(t, {"u": np.full(3, float(t))})
    files = glob.glob(str(tmp_path / "step_*.npz"))
    assert len(files) == 4

    monkeypatch.setenv("PCG_TPU_SNAP_KEEP", "not-a-number")
    with pytest.warns(UserWarning, match="PCG_TPU_SNAP_KEEP"):
        assert store.retention() == 2


def test_snapshot_latest_skips_corrupt(tmp_path):
    from pcg_mpi_solver_tpu.utils.checkpoint import SnapshotStore

    store = SnapshotStore(str(tmp_path), None, prefix="step")
    store.save(3, {"u": np.ones(2)})
    store.save(4, {"u": np.ones(2)})
    newest = str(tmp_path / "step_000004.npz")
    blob = open(newest, "rb").read()
    with open(newest, "wb") as f:
        f.write(blob[: len(blob) // 3])
    assert store.latest() == 3      # corrupt newest costs one slot
    # the two prefixes never cross: a snap_* store sees nothing here
    assert SnapshotStore(str(tmp_path), None).latest() is None


# ----------------------------------------------------------------------
# Newmark: ladder, kill-and-resume bit-identity, NaN rollback
# ----------------------------------------------------------------------

def test_newmark_per_step_ladder(tmp_path, model):
    """A rho0 breakdown injected at a chunk boundary inside a Newmark
    step recovers through the shared ladder (restart_minres) and still
    converges — the driver-layer posture, now on the Newmark path."""
    cap = _Capture()
    s = NewmarkSolver(model, _ncfg(tmp_path, "lad", ipd=7),
                      mesh=make_mesh(2), n_parts=2, dt=0.2,
                      recorder=MetricsRecorder(sinks=[cap]))
    s.fault_plan = FaultPlan("rho0@1", recorder=s.recorder)
    res = s.run(DELTAS)
    assert all(r.flag == 0 for r in res)
    recs = [(e["action"], e["trigger"]) for e in cap.events
            if e["kind"] == "recovery"]
    assert ("restart_minres", "flag4") in recs


def test_newmark_block3_fallback_prec(tmp_path, model):
    """Ladder rung 2 on the SHIFTED operator: block3 breakdowns retry
    under the scalar-Jacobi fallback of A = K + c*M."""
    cap = _Capture()
    # ipd=3 + tight tol: enough chunk boundaries inside step 1 (before
    # AND after the first restart) that both injected breakdowns hit the
    # SAME step's ladder (rung 1, then rung 2)
    s = NewmarkSolver(model, _ncfg(tmp_path, "fb", ipd=3,
                                   precond="block3", tol=1e-13),
                      mesh=make_mesh(2), n_parts=2, dt=0.2,
                      recorder=MetricsRecorder(sinks=[cap]))
    s.fault_plan = FaultPlan("rho0@1,rho0@2", recorder=s.recorder)
    res = s.run(DELTAS)
    assert all(r.flag == 0 for r in res)
    recs = [(e["action"], e["attempt"]) for e in cap.events
            if e["kind"] == "recovery"]
    assert ("fallback_prec", 2) in recs, recs


def test_newmark_kill_and_resume_bit_identity(tmp_path, model):
    """ISSUE 4 acceptance: a PCG_TPU_FAULTS-injected kill at timestep N
    of a Newmark run, followed by --resume, reproduces the uninterrupted
    run's displacement history and trace ring bit-identically."""
    ref = NewmarkSolver(model, _ncfg(tmp_path, "ref", ipd=7, trace=32),
                        mesh=make_mesh(2), n_parts=2, dt=0.2)
    ref.run(DELTAS)

    cap = _Capture()
    kcfg = _ncfg(tmp_path, "kill", ipd=7, snap=1, trace=32)
    k1 = NewmarkSolver(model, kcfg, mesh=make_mesh(2), n_parts=2, dt=0.2)
    k1.fault_plan = FaultPlan("kill@s:3")
    with pytest.raises(SimulatedKill):
        k1.run(DELTAS)
    snaps = glob.glob(os.path.join(kcfg.checkpoint_path, "step_*.npz"))
    assert snaps, "the kill must leave timestep snapshots behind"

    k2 = NewmarkSolver(model, kcfg, mesh=make_mesh(2), n_parts=2, dt=0.2,
                       recorder=MetricsRecorder(sinks=[cap]))
    res = k2.run(DELTAS, resume=True)
    assert len(res) == 2            # steps 4..5 only
    assert k2.flags == ref.flags and k2.iters == ref.iters
    assert k2.relres == ref.relres
    for a, b in zip(k2.state_global(), ref.state_global()):
        np.testing.assert_array_equal(a, b)
    # the per-step convergence ring of the resumed steps matches exactly
    np.testing.assert_array_equal(k2.last_trace.normr,
                                  ref.last_trace.normr)
    assert [e["op"] for e in cap.events
            if e["kind"] == "step_snapshot"][0] == "restore"


def test_newmark_resume_schedule_mismatch(tmp_path, model):
    cfg = _ncfg(tmp_path, "sched", snap=1)
    s = NewmarkSolver(model, cfg, mesh=make_mesh(2), n_parts=2, dt=0.2)
    s.fault_plan = FaultPlan("kill@s:2")
    with pytest.raises(SimulatedKill):
        s.run(DELTAS)
    s2 = NewmarkSolver(model, cfg, mesh=make_mesh(2), n_parts=2, dt=0.2)
    with pytest.raises(ValueError, match="schedule mismatch"):
        s2.run([9.0] * 5, resume=True)


def test_newmark_nan_rollback(tmp_path, model):
    """A NaN injected into the kinematic state at timestep N rolls back
    to the last step snapshot and re-integrates — final state
    bit-identical to a clean run, with a rollback recovery event."""
    ref = NewmarkSolver(model, _ncfg(tmp_path, "c0"), mesh=make_mesh(2),
                        n_parts=2, dt=0.2)
    ref.run(DELTAS)
    cap = _Capture()
    s = NewmarkSolver(model, _ncfg(tmp_path, "c1", snap=1),
                      mesh=make_mesh(2), n_parts=2, dt=0.2,
                      recorder=MetricsRecorder(sinks=[cap]))
    s.fault_plan = FaultPlan("nan@s:2", recorder=s.recorder)
    res = s.run(DELTAS)
    assert all(r.flag == 0 for r in res)
    assert s.flags == ref.flags and s.iters == ref.iters
    np.testing.assert_array_equal(s.state_global()[0],
                                  ref.state_global()[0])
    rolls = [e for e in cap.events if e["kind"] == "recovery"
             and e["action"] == "rollback"]
    assert rolls and rolls[0]["trigger"] == "nan_carry"


def test_newmark_rollback_budget_exhausts(tmp_path, model):
    """Persistent poison exhausts max_recoveries into an honest
    FloatingPointError instead of looping forever."""
    s = NewmarkSolver(model,
                      _ncfg(tmp_path, "bud", snap=1, max_recoveries=2),
                      mesh=make_mesh(2), n_parts=2, dt=0.2)
    s.fault_plan = FaultPlan("nan@s:1,nan@s:2,nan@s:3")
    with pytest.raises(FloatingPointError, match="non-finite"):
        s.run(DELTAS)


# ----------------------------------------------------------------------
# Explicit dynamics: kill-and-resume, NaN rollback, chunk splitting
# ----------------------------------------------------------------------

def _dcfg(tmp_path, run_id, snap=0):
    cfg = RunConfig(scratch_path=str(tmp_path), run_id=run_id)
    cfg.snapshot_every = snap
    return cfg


def test_dynamics_kill_and_resume_bit_identity(tmp_path, dyn_model):
    """Kill at timestep N mid explicit history; resume reproduces the
    uninterrupted run's probe series and export frames bit-identically
    (the probe series is the explicit path's 'trace ring')."""
    dt = stable_dt(dyn_model, safety=0.5)
    ref = DynamicsSolver(dyn_model, _dcfg(tmp_path, "r"),
                         mesh=make_mesh(4), n_parts=4, dt=dt,
                         damping=0.05, probe_dofs=(6, 13))
    res_ref = ref.run(25, export_every=5)

    kcfg = _dcfg(tmp_path, "k", snap=4)
    d1 = DynamicsSolver(dyn_model, kcfg, mesh=make_mesh(4), n_parts=4,
                        dt=dt, damping=0.05, probe_dofs=(6, 13))
    d1.fault_plan = FaultPlan("kill@s:12")
    with pytest.raises(SimulatedKill):
        d1.run(25, export_every=5)
    # retention bound holds mid-history (default keep 2)
    snaps = sorted(os.path.basename(p) for p in glob.glob(
        os.path.join(kcfg.checkpoint_path, "step_*.npz")))
    assert snaps == ["step_000008.npz", "step_000012.npz"]

    d2 = DynamicsSolver(dyn_model, kcfg, mesh=make_mesh(4), n_parts=4,
                        dt=dt, damping=0.05, probe_dofs=(6, 13))
    res = d2.run(25, export_every=5, resume=True)
    np.testing.assert_array_equal(res.probe_u, res_ref.probe_u)
    np.testing.assert_array_equal(res.u, res_ref.u)
    assert res.frame_times == res_ref.frame_times
    for a, b in zip(res.frames, res_ref.frames):
        np.testing.assert_array_equal(a, b)


def test_dynamics_nan_rollback_bit_identity(tmp_path, dyn_model):
    dt = stable_dt(dyn_model, safety=0.5)
    ref = DynamicsSolver(dyn_model, _dcfg(tmp_path, "r2"),
                         mesh=make_mesh(4), n_parts=4, dt=dt,
                         damping=0.05, probe_dofs=(6,))
    res_ref = ref.run(25, export_every=5)
    cap = _Capture()
    d = DynamicsSolver(dyn_model, _dcfg(tmp_path, "n2", snap=5),
                       mesh=make_mesh(4), n_parts=4, dt=dt,
                       damping=0.05, probe_dofs=(6,),
                       recorder=MetricsRecorder(sinks=[cap]))
    d.fault_plan = FaultPlan("nan@s:10", recorder=d.recorder)
    res = d.run(25, export_every=5)
    np.testing.assert_array_equal(res.probe_u, res_ref.probe_u)
    np.testing.assert_array_equal(res.u, res_ref.u)
    assert [e["action"] for e in cap.events
            if e["kind"] == "recovery"] == ["rollback"]


def test_dynamics_unguarded_nonfinite_raises(dyn_model):
    """Without snapshots there is nothing to roll back to: the run must
    fail loudly instead of silently integrating garbage (the historical
    behavior was to return NaN results with no signal)."""
    dt = stable_dt(dyn_model, safety=0.5)
    d = DynamicsSolver(dyn_model, RunConfig(), mesh=make_mesh(1),
                       n_parts=1, dt=dt)
    d.fault_plan = FaultPlan("nan@s:3")
    with pytest.raises(FloatingPointError, match="non-finite"):
        d.run(10)


def test_dynamics_chunk_splitting_is_bitwise_neutral(tmp_path, dyn_model):
    """Snapshot-cadence chunk splitting changes the device dispatch
    pattern but not the per-step math: probe series bit-identical to
    the single-chunk run."""
    dt = stable_dt(dyn_model, safety=0.5)
    a = DynamicsSolver(dyn_model, _dcfg(tmp_path, "s0"),
                       mesh=make_mesh(2), n_parts=2, dt=dt,
                       probe_dofs=(6,))
    ra = a.run(20)
    b = DynamicsSolver(dyn_model, _dcfg(tmp_path, "s3", snap=3),
                       mesh=make_mesh(2), n_parts=2, dt=dt,
                       probe_dofs=(6,))
    rb = b.run(20)
    np.testing.assert_array_equal(ra.probe_u, rb.probe_u)
    np.testing.assert_array_equal(ra.u, rb.u)
