"""PCG correctness: solves the system, matches scipy, iteration-count parity
across partition counts (the invariant the reference preserves when scaling
ranks), and MATLAB-compatible edge-case flags."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.driver import Solver


def scipy_solution(model, tol=1e-10):
    """Direct sparse solve on effective dofs with Dirichlet lifting."""
    from scipy.sparse.linalg import spsolve

    K = model.assemble_csr()
    eff = model.dof_eff
    rhs = (model.F - K @ model.Ud)[eff]
    u = np.array(model.Ud)
    u[eff] += spsolve(K[eff][:, eff].tocsc(), rhs)
    return u


def make_solver(model, n_parts, tol=1e-8, mesh=None, **kw):
    cfg = RunConfig(
        solver=SolverConfig(tol=tol, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    mesh = mesh or make_mesh(1)
    return Solver(model, cfg, mesh=mesh, n_parts=n_parts, **kw)


@pytest.mark.parametrize("load", ["traction", "dirichlet"])
def test_pcg_matches_direct_solve(load):
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load=load, heterogeneous=True)
    s = make_solver(model, 1)
    res = s.step(1.0)
    assert res.flag == 0
    assert res.relres <= 1e-8
    u = s.displacement_global()
    u_ref = scipy_solution(model)
    np.testing.assert_allclose(u, u_ref, rtol=1e-5, atol=1e-8 * np.abs(u_ref).max())


def test_iteration_parity_across_partitions():
    """Same iteration count and residual for 1, 4, 8 parts — domain
    decomposition must not change the math (SURVEY.md §7 step 2)."""
    model = make_cube_model(5, 4, 4, heterogeneous=True)
    results = {}
    for n_parts, n_dev in [(1, 1), (4, 4), (8, 8)]:
        s = make_solver(model, n_parts, mesh=make_mesh(n_dev))
        results[n_parts] = s.step(1.0)
    i1 = results[1].iters
    for n_parts in (4, 8):
        assert results[n_parts].flag == 0
        assert abs(results[n_parts].iters - i1) <= 1
        assert np.isclose(results[n_parts].relres, results[1].relres, rtol=0.5)


def test_pcg_zero_rhs():
    """All-zero rhs => all-zero solution, flag 0, 0 iterations
    (reference pcg_solver.py:387-395)."""
    model = make_cube_model(3, 3, 3)
    model.F[:] = 0.0
    model.Ud[:] = 0.0
    s = make_solver(model, 1)
    res = s.step(1.0)
    assert res.flag == 0 and res.iters == 0 and res.relres == 0.0
    assert np.all(s.displacement_global() == 0.0)


def test_pcg_warm_start_early_exit():
    """Re-solving from the converged state exits immediately
    (good-initial-guess path, pcg_solver.py:421-426)."""
    model = make_cube_model(3, 3, 3)
    s = make_solver(model, 1)
    r1 = s.step(1.0)
    assert r1.flag == 0
    r2 = s.step(1.0)
    assert r2.flag == 0
    assert r2.iters <= 1


def test_multistep_dirichlet_lifting():
    """Ramped prescribed displacement: u scales linearly with delta(t) in a
    linear problem (reference updateBC, pcg_solver.py:226-238)."""
    model = make_cube_model(3, 3, 3, load="dirichlet")
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-10, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 0.5, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    res = s.solve()
    assert all(r.flag == 0 for r in res)
    u_half = None
    # step through manually to capture intermediate states
    s2 = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    s2.step(0.5)
    u_half = s2.displacement_global()
    s2.step(1.0)
    u_full = s2.displacement_global()
    np.testing.assert_allclose(u_full, 2.0 * u_half, rtol=1e-5, atol=1e-10)


def test_plateau_window_mechanism():
    """The experimental plateau exit (off by default): a short window cuts
    an f32 solve at floor earlier than MATLAB's stagnation protocol and
    returns the min-residual iterate; window=0 is exactly the MATLAB
    behavior.  Also pins WHY it is off by default: a too-short window
    false-triggers during CG's non-monotone pre-asymptotic phase."""
    from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
    from pcg_mpi_solver_tpu.parallel.partition import partition_model
    from pcg_mpi_solver_tpu.solver.pcg import pcg

    model = make_cube_model(6, 5, 5, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    pm = partition_model(model, 1)
    data = device_data(pm, jnp.float32)
    ops = Ops.from_model(pm, dot_dtype=jnp.float32)
    eff = data["eff"]
    fext = eff * data["F"]
    x0 = jnp.zeros_like(fext)
    d = eff * ops.diag(data)
    inv_diag = jnp.where(d != 0, 1.0 / jnp.maximum(d, 1e-30), 0.0)
    kw = dict(tol=1e-14, max_iter=1500,
              glob_n_dof_eff=int(model.dof_eff.sum()))
    res_full = pcg(ops, data, fext, x0, inv_diag, plateau_window=0, **kw)
    res_plat = pcg(ops, data, fext, x0, inv_diag, plateau_window=10, **kw)
    res_tiny = pcg(ops, data, fext, x0, inv_diag, plateau_window=5, **kw)
    # MATLAB protocol alone: stagnation + MoreSteps end the grind
    assert int(res_full.flag) == 3
    # window=10 exits earlier with a min-residual iterate of useful quality
    assert int(res_plat.flag) == 3
    assert int(res_plat.iters) < int(res_full.iters)
    assert float(res_plat.relres) < 1e-2
    # the false-trigger hazard (the reason the default is off): a 5-iter
    # window fires inside the pre-asymptotic residual wander
    assert int(res_tiny.iters) < 10


def test_progress_exit_mechanism():
    """The progress-rate exit (mixed-mode inner cycles): plumbing fires
    when armed with hair-trigger thresholds, and the min-gain gate keeps
    it unreachable before the cycle has done real work."""
    from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
    from pcg_mpi_solver_tpu.parallel.partition import partition_model
    from pcg_mpi_solver_tpu.solver.pcg import pcg

    model = make_cube_model(6, 5, 5, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    pm = partition_model(model, 1)
    data = device_data(pm, jnp.float32)
    ops = Ops.from_model(pm, dot_dtype=jnp.float32)
    eff = data["eff"]
    fext = eff * data["F"]
    x0 = jnp.zeros_like(fext)
    d = eff * ops.diag(data)
    inv_diag = jnp.where(d != 0, 1.0 / jnp.maximum(d, 1e-30), 0.0)
    kw = dict(tol=1e-14, max_iter=1500,
              glob_n_dof_eff=int(model.dof_eff.sum()))
    res_off = pcg(ops, data, fext, x0, inv_diag, **kw)
    # hair-trigger: 1-iter window, any ratio counts as weak, gate at 1.5x
    # achieved contraction -> exits very early with the min-residual
    # iterate (proves the window/gate plumbing end to end)
    res_trip = pcg(ops, data, fext, x0, inv_diag, progress_window=1,
                   progress_ratio=1e-9, progress_min_gain=1.5, **kw)
    assert int(res_trip.flag) == 3
    assert int(res_trip.iters) < int(res_off.iters)
    # production thresholds: the min-gain gate (30x) plus the long window
    # must leave this small f32-floor grind to MATLAB's own stagnation
    # protocol — identical iteration count and flag as knob-off
    res_prod = pcg(ops, data, fext, x0, inv_diag, progress_window=150,
                   progress_ratio=0.7, progress_min_gain=30.0, **kw)
    assert int(res_prod.flag) == int(res_off.flag)
    assert int(res_prod.iters) == int(res_off.iters)
    assert float(res_prod.relres) == float(res_off.relres)


def test_mixed_progress_default_no_small_scale_regression():
    """mixed_progress_window (opt-in since the negative 96^3 A/B,
    docs/BENCH_LOG.md 2026-08-01): a small mixed solve must converge
    identically (flag 0, same tol) with it on or off — the min-gain gate
    keeps pre-asymptotic windows unreachable."""
    model = make_cube_model(5, 4, 4, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    results = {}
    for win in (0, 150):
        cfg = RunConfig(
            solver=SolverConfig(tol=1e-9, max_iter=4000, dtype="float32",
                                dot_dtype="float64", precision_mode="mixed",
                                mixed_progress_window=win),
            time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
        )
        s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
        results[win] = s.step(1.0)
    assert results[150].flag == 0
    assert results[150].iters == results[0].iters
    assert np.isclose(results[150].relres, results[0].relres, rtol=1e-6)


@pytest.mark.parametrize("fault,flag_name", [("rho0@1", "flag4"),
                                             ("inf@1", "flag2")])
def test_breakdown_ladder_recovers_to_converged(fault, flag_name):
    """Engineered flag-4 (rho/pq breakdown via a zeroed carry rho — the
    resumed beta recurrence divides by zero) and flag-2 (Inf
    preconditioner via an Inf residual) inputs on the chunked path: the
    recovery ladder (resilience/) must restart from the min-residual
    iterate and finish at flag=0 within the default retry budget, with
    the recovery visible as a telemetry event (ISSUE 3 acceptance b)."""
    from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
    from pcg_mpi_solver_tpu.resilience import FaultPlan

    class Cap:
        def __init__(self):
            self.events = []

        def emit(self, ev):
            self.events.append(ev)

        def close(self):
            pass

    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000, iters_per_dispatch=15),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    cap = Cap()
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1,
               recorder=MetricsRecorder(sinks=[cap]))
    s.fault_plan = FaultPlan(fault, recorder=s.recorder)
    res = s.step(1.0)
    assert res.flag == 0
    assert res.relres <= 1e-8
    recoveries = [e for e in cap.events if e["kind"] == "recovery"]
    assert [(e["action"], e["trigger"]) for e in recoveries] == \
        [("restart_minres", flag_name)]
    # the recovered solution is the true solution, not just a flag
    u_ref = scipy_solution(model)
    np.testing.assert_allclose(s.displacement_global(), u_ref, rtol=1e-5,
                               atol=1e-8 * np.abs(u_ref).max())


def test_mixed_converges_with_plateau_default():
    model = make_cube_model(5, 4, 4, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-9, max_iter=4000, dtype="float32",
                            dot_dtype="float64", precision_mode="mixed"),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    r = s.step(1.0)
    assert r.flag == 0 and r.relres <= 1e-9
    u = np.asarray(s.displacement_global())
    np.testing.assert_allclose(u, scipy_solution(model), rtol=0,
                               atol=1e-7 * np.abs(scipy_solution(model)).max())
