"""Fused-collective (Chronopoulos–Gear) PCG variant
(SolverConfig.pcg_variant="fused"): convergence parity with classic on
the golden model, chunked-dispatch and kill-and-resume bit-identity,
recovery-ladder compatibility under fault injection, and the end-to-end
config plumbing (CLI flag, cache key, bench detail field).  The
single-psum-per-iteration claim itself is proven statically in
tests/test_collectives.py."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.resilience import FaultPlan, SimulatedKill
from pcg_mpi_solver_tpu.solver.driver import Solver


@pytest.fixture(scope="module")
def model():
    # the golden cube (tests/test_goldens.py): 6x5x5 heterogeneous
    return make_cube_model(6, 5, 5, h=0.5, nu=0.3, heterogeneous=True,
                           seed=0)


def _cfg(variant, tmp_path=None, run_id="1", **solver_kw):
    solver_kw.setdefault("tol", 1e-8)
    solver_kw.setdefault("max_iter", 2000)
    cfg = RunConfig(
        solver=SolverConfig(pcg_variant=variant, **solver_kw),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                       export_flag=False),
    )
    cfg.run_id = run_id
    if tmp_path is not None:
        cfg.scratch_path = str(tmp_path)
    return cfg


def _iters_close(fused, classic):
    """Acceptance bar: fused iteration count within +/-5% of classic
    (+2 absolute slack for the pipelined one-trip lag on tiny counts)."""
    assert abs(fused - classic) <= max(2, int(0.05 * classic) + 1), \
        (fused, classic)


# ----------------------------------------------------------------------
# Convergence parity (golden + scipy)
# ----------------------------------------------------------------------

def test_fused_parity_direct_golden(model):
    """flag=0 on the golden model, iteration count within the documented
    tolerance of classic, identical solution to ~tol."""
    rs = {}
    for variant in ("classic", "fused"):
        s = Solver(model, _cfg(variant), mesh=make_mesh(4), n_parts=4)
        rs[variant] = (s.step(1.0),
                       float(np.abs(s.displacement_global()).sum()))
    rc, cc = rs["classic"]
    rf, cf = rs["fused"]
    assert rc.flag == 0 and rf.flag == 0
    assert rf.relres <= 1e-8 * 1.001
    _iters_close(rf.iters, rc.iters)
    assert np.isclose(cf, cc, rtol=1e-6)


def test_fused_parity_mixed(model):
    """Mixed precision with fused f32 inner cycles: converges to the
    outer tolerance with a comparable total inner-iteration count."""
    rs = {}
    for variant in ("classic", "fused"):
        s = Solver(model, _cfg(variant, precision_mode="mixed"),
                   mesh=make_mesh(4), n_parts=4)
        rs[variant] = s.step(1.0)
    assert rs["classic"].flag == 0 and rs["fused"].flag == 0
    assert rs["fused"].relres <= 1e-8 * 1.001
    _iters_close(rs["fused"].iters, rs["classic"].iters)


def test_fused_matches_scipy():
    from scipy.sparse.linalg import spsolve

    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    s = Solver(model, _cfg("fused"), mesh=make_mesh(1), n_parts=1)
    res = s.step(1.0)
    assert res.flag == 0
    K = model.assemble_csr()
    eff = model.dof_eff
    rhs = (model.F - K @ model.Ud)[eff]
    u_ref = np.array(model.Ud)
    u_ref[eff] += spsolve(K[eff][:, eff].tocsc(), rhs)
    np.testing.assert_allclose(s.displacement_global(), u_ref,
                               rtol=1e-5, atol=1e-8 * np.abs(u_ref).max())


def test_fused_trace_ring(model):
    """The in-graph convergence ring works unchanged under the fused
    body (one slot per resolved iteration, monotone-ish tail)."""
    s = Solver(model, _cfg("fused", trace_resid=64),
               mesh=make_mesh(1), n_parts=1)
    res = s.step(1.0)
    assert res.flag == 0
    tr = s.last_trace
    assert tr is not None and tr.n_recorded > 0
    assert tr.flag[-1] == 0                    # converged slot recorded
    assert tr.normr[-1] < tr.normr[0]


# ----------------------------------------------------------------------
# Resumable dispatch: chunked bit-identity, kill-and-resume
# ----------------------------------------------------------------------

def test_fused_chunked_bit_identical_to_oneshot(model):
    """The q/alpha/fresh recurrence state rides the resumable carry, so
    capped fused dispatches are bit-identical to one long fused solve
    (the classic chunked contract, tests/test_chunked.py)."""
    s1 = Solver(model, _cfg("fused"), mesh=make_mesh(4), n_parts=4)
    r1 = s1.step(1.0)
    s2 = Solver(model, _cfg("fused", iters_per_dispatch=12),
                mesh=make_mesh(4), n_parts=4)
    r2 = s2.step(1.0)
    assert r1.flag == r2.flag == 0
    assert r1.iters == r2.iters
    assert r1.relres == r2.relres
    np.testing.assert_array_equal(s1.displacement_global(),
                                  s2.displacement_global())


def test_fused_snapshot_kill_resume_bit_identity(model, tmp_path):
    """Mid-Krylov snapshot/resume on the chunked path round-trips the
    fused carry (incl. q/alpha/fresh): a kill at a chunk boundary plus
    --resume reproduces the uninterrupted solve bit-identically."""
    def cfg(run_id):
        c = _cfg("fused", tmp_path, run_id=run_id,
                 iters_per_dispatch=12)
        c.checkpoint_every = 1
        c.snapshot_every = 1
        return c

    sa = Solver(model, cfg("fa"), mesh=make_mesh(4), n_parts=4)
    sa.solve()
    ck = cfg("fk")
    sk = Solver(model, ck, mesh=make_mesh(4), n_parts=4)
    sk.fault_plan = FaultPlan("kill@2")
    with pytest.raises(SimulatedKill):
        sk.solve()
    sk2 = Solver(model, ck, mesh=make_mesh(4), n_parts=4)
    sk2.solve(resume=True)
    assert sk2.flags == sa.flags and sk2.iters == sa.iters
    assert sk2.relres == sa.relres
    np.testing.assert_array_equal(sk2.displacement_global(),
                                  sa.displacement_global())


# ----------------------------------------------------------------------
# Recovery-ladder compatibility (PR-3/PR-4 stack)
# ----------------------------------------------------------------------

class _Capture:
    def __init__(self):
        self.events = []

    def emit(self, ev):
        self.events.append(ev)

    def close(self):
        pass


@pytest.mark.parametrize("fault,trigger", [
    ("rho0@1", "flag4"),       # zeroed rho => beta Inf => flag-4 breakdown
    ("nan@1", "nan_carry"),    # NaN trips no in-graph flag; host detects
])
def test_fused_fault_recovery(model, fault, trigger):
    """flag-2/4 breakdowns and NaN poisoning climb the same recovery
    ladder under the fused recurrence and still converge."""
    cap = _Capture()
    s = Solver(model, _cfg("fused", iters_per_dispatch=12),
               mesh=make_mesh(1), n_parts=1,
               recorder=MetricsRecorder(sinks=[cap]))
    s.fault_plan = FaultPlan(fault, recorder=s.recorder)
    res = s.step(1.0)
    assert res.flag == 0 and res.relres <= 1e-8
    recs = [(e["action"], e["trigger"]) for e in cap.events
            if e["kind"] == "recovery"]
    assert ("restart_minres", trigger) in recs


def test_fused_mixed_escalates_to_f64(model):
    """Ladder rung 3 under fused: repeated mixed-path corruption
    escalates to direct-f64 cycles (themselves fused) and converges."""
    cap = _Capture()
    cfg = _cfg("fused", precision_mode="mixed", dtype="float32",
               dot_dtype="float64", tol=1e-9, max_iter=4000,
               inner_tol=0.1, max_recoveries=3, iters_per_dispatch=12)
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1,
               recorder=MetricsRecorder(sinks=[cap]))
    s.fault_plan = FaultPlan("inf@0,inf@1", recorder=s.recorder)
    res = s.step(1.0)
    assert res.flag == 0 and res.relres <= 1e-9
    recs = [(e["action"], e["trigger"]) for e in cap.events
            if e["kind"] == "recovery"]
    assert ("escalate_f64", "nan_carry") in recs


# ----------------------------------------------------------------------
# Newmark per-step solves
# ----------------------------------------------------------------------

def test_fused_newmark_steps_match_classic():
    from pcg_mpi_solver_tpu.solver.newmark import NewmarkSolver

    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    us = {}
    for variant in ("classic", "fused"):
        s = NewmarkSolver(model, _cfg(variant), mesh=make_mesh(1),
                          n_parts=1, dt=1e-5)
        res = s.run([1.0, 1.0, 1.0])
        assert all(r.flag == 0 for r in res), variant
        us[variant] = s.displacement_global()
    np.testing.assert_allclose(us["fused"], us["classic"], rtol=1e-5,
                               atol=1e-10 * np.abs(us["classic"]).max())


# ----------------------------------------------------------------------
# Config plumbing surfaces
# ----------------------------------------------------------------------

def test_invalid_variant_rejected(model):
    with pytest.raises(ValueError, match="pcg_variant"):
        Solver(model, _cfg("frobnicate"), mesh=make_mesh(1), n_parts=1)

    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.solver.pcg import pcg

    with pytest.raises(ValueError, match="variant"):
        pcg(None, None, jnp.zeros((1, 3)), jnp.zeros((1, 3)),
            jnp.ones((1, 3)), tol=1e-8, max_iter=5, glob_n_dof_eff=3,
            variant="bogus")


def test_cache_key_separates_variants():
    from pcg_mpi_solver_tpu.cache.keys import step_cache_key

    kw = dict(abstract="sig", mesh=("m", "cpu"), backend="general",
              solver={"tol": 1e-8}, trace_len=0, glob_n_dof_eff=100,
              donate=True, jax_version="x")
    assert step_cache_key(pcg_variant="classic", **kw) != \
        step_cache_key(pcg_variant="fused", **kw)


def test_cli_flag_plumbs_variant():
    from types import SimpleNamespace

    from pcg_mpi_solver_tpu.cli import _load_settings

    args = SimpleNamespace(settings=None, tol=None, max_iter=None,
                           precision=None, precond=None,
                           pcg_variant="fused")
    cfg = _load_settings(None, args)
    assert cfg.solver.pcg_variant == "fused"
    args.pcg_variant = None
    assert _load_settings(None, args).solver.pcg_variant == "classic"


def test_bench_detail_reports_variant():
    from types import SimpleNamespace

    from pcg_mpi_solver_tpu.bench import _run_config_extra

    solver = SimpleNamespace(
        backend="general", ops=SimpleNamespace(),
        config=SimpleNamespace(solver=SimpleNamespace(
            pcg_variant="fused")))
    extra = _run_config_extra(solver, "float32", "mixed", False, 1, 0.1,
                              "cpu")
    assert extra["pcg_variant"] == "fused"


def test_run_summary_carries_variant_gauge(model):
    cap = _Capture()
    s = Solver(model, _cfg("fused"), mesh=make_mesh(1), n_parts=1,
               recorder=MetricsRecorder(sinks=[cap]))
    s.step(1.0)
    s.recorder.emit_run_summary()
    summaries = [e for e in cap.events if e["kind"] == "run_summary"]
    assert summaries and summaries[-1]["gauges"]["pcg_variant"] == "fused"
    assert summaries[-1]["gauges"]["comm.pcg_variant"] == "fused"
    # fused drops the two serialized scalar psums from the gauge too
    assert summaries[-1]["gauges"]["comm.psums_per_iter"] == \
        s.ops.comm_estimate(variant="fused")["psums_per_iter"]


def test_cross_variant_resume_rejected_by_fingerprint(model, tmp_path):
    """A checkpoint written under one variant must be rejected on resume
    under the other with a clear fingerprint mismatch — the fused carry
    rides extra pytree leaves (q/alpha/fresh), so without the guard the
    failure would be an obscure shard_map structure error (or a silently
    different iteration sequence)."""
    cfg_f = _cfg("fused", tmp_path, run_id="xv",
                 iters_per_dispatch=12)
    cfg_f.checkpoint_every = 1
    s = Solver(model, cfg_f, mesh=make_mesh(1), n_parts=1)
    s.solve()

    cfg_c = _cfg("classic", tmp_path, run_id="xv",
                 iters_per_dispatch=12)
    cfg_c.checkpoint_every = 1
    s2 = Solver(model, cfg_c, mesh=make_mesh(1), n_parts=1)
    with pytest.raises(ValueError, match="pcg_variant"):
        s2.solve(resume=True)


# ----------------------------------------------------------------------
# Residual-drift guard (ISSUE 9 satellite, arXiv:2501.03743): the fused
# deferred true-residual check counts disagreements with the recurrence
# norm and exits recoverably (flag 6) on sustained drift.
# ----------------------------------------------------------------------

_DRIFT_SETUP = {}


def _direct_pcg_setup(nx=5):
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
    from pcg_mpi_solver_tpu.parallel.partition import partition_model

    if nx in _DRIFT_SETUP:
        return _DRIFT_SETUP[nx]
    m = make_cube_model(nx, 4, 4, h=0.5, nu=0.3, load="traction",
                        heterogeneous=True)
    pm = partition_model(m, 1)
    data = device_data(pm, jnp.float64)
    ops = Ops.from_model(pm, dot_dtype=jnp.float64)
    eff = data["eff"]
    fext = eff * data["F"]
    d = eff * ops.diag(data)
    inv = jnp.where(d != 0, 1.0 / jnp.where(d != 0, d, 1.0), 0.0)
    _DRIFT_SETUP[nx] = (m, ops, data, fext, inv)
    return _DRIFT_SETUP[nx]


def test_fused_drift_guard_exits_flag6_and_counts():
    """Recurrence drift re-emerges after every self-correcting deferred
    check (the check resets r to truth, but a drifting recurrence lies
    again) — emulated by re-poisoning the carry residual before each
    capped dispatch.  Each poisoned dispatch's check disagrees (>2x)
    and counts into the resumable ``drift`` leaf; at FUSED_DRIFT_LIMIT
    the solve exits with the recoverable DRIFT_FLAG instead of grinding
    on the stale norm, and breakdown_trigger routes it to the ladder."""
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.resilience import breakdown_trigger
    from pcg_mpi_solver_tpu.solver.pcg import (
        DRIFT_FLAG, FUSED_DRIFT_LIMIT, pcg)

    import jax

    m, ops, data, fext, inv = _direct_pcg_setup()
    kw = dict(tol=1e-8, max_iter=1, max_iter_nominal=200,
              glob_n_dof_eff=int(np.asarray(m.dof_eff).sum()),
              variant="fused", return_carry=True)
    res, carry = pcg(ops, data, fext, jnp.zeros_like(fext), inv,
                     **dict(kw, max_iter=5))
    assert int(carry["drift"]) == 0, "healthy fused solve: no drift"
    # one jitted resumable dispatch, re-run per poisoned carry (the
    # shapes never change, so the loop pays one trace)
    step = jax.jit(lambda c: pcg(ops, data, fext, jnp.zeros_like(fext),
                                 inv, carry_in=c, **kw))
    for k in range(FUSED_DRIFT_LIMIT):
        # the recurrence claims convergence; the true residual disagrees
        carry = dict(carry)
        carry["r"] = carry["r"] * 1e-14
        res, carry = step(carry)
        assert int(carry["drift"]) == k + 1
    assert int(res.flag) == DRIFT_FLAG
    assert breakdown_trigger(int(res.flag), float(res.relres)) == "flag6"


def test_fused_drift_guard_per_column():
    """Blocked twin: only the column whose recurrence keeps lying exits
    flag 6 and counts drift; the healthy column's state is untouched
    (per-column drift isolation)."""
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.solver.pcg import (
        DRIFT_FLAG, FUSED_DRIFT_LIMIT, pcg_many)

    import jax

    m, ops, data, fext1, inv = _direct_pcg_setup()
    fb = jnp.stack([fext1, 0.5 * fext1], axis=-1)
    kw = dict(tol=1e-8, max_iter=1, max_iter_nominal=200,
              glob_n_dof_eff=int(np.asarray(m.dof_eff).sum()),
              variant="fused", return_carry=True)
    res, carry = pcg_many(ops, data, fb, jnp.zeros_like(fb), inv,
                          **dict(kw, max_iter=5))
    lie = jnp.asarray([1e-14, 1.0])
    step = jax.jit(lambda c: pcg_many(ops, data, fb,
                                      jnp.zeros_like(fb), inv,
                                      carry_in=c, **kw))
    for _ in range(FUSED_DRIFT_LIMIT):
        carry = dict(carry)
        carry["r"] = carry["r"] * lie[None, None, :]
        res, carry = step(carry)
    assert int(res.flag[0]) == DRIFT_FLAG
    assert int(carry["drift"][0]) >= FUSED_DRIFT_LIMIT
    assert int(res.flag[1]) != DRIFT_FLAG
    assert int(carry["drift"][1]) == 0
