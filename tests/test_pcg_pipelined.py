"""Pipelined (Ghysels–Vanroose depth-1) PCG variant
(SolverConfig.pcg_variant="pipelined", ISSUE 11): convergence parity
with classic on the golden model, chunked-dispatch and kill-and-resume
bit-identity, cross-variant resume rejection, recovery-ladder
compatibility under fault injection, the tighter flag-6 drift guard,
MG composition, and the single-source variant-table plumbing (config /
cache key / CLI / collective tables).  The overlap claim itself — the
body's one fused psum is data-independent of the stencil matvec — is
proven statically by the analysis/ psum-overlap rule
(tests/test_analysis.py seeds its violations); here the same dependency
walker is run once against the REAL traced pipelined loop so tier-1
covers the claim without the full (slow) lint matrix."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import (
    PCG_VARIANTS, RunConfig, SolverConfig, TimeHistoryConfig)
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs.metrics import MetricsRecorder
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.resilience import FaultPlan, SimulatedKill
from pcg_mpi_solver_tpu.solver.driver import Solver


@pytest.fixture(scope="module")
def model():
    # the golden cube (tests/test_goldens.py): 6x5x5 heterogeneous
    return make_cube_model(6, 5, 5, h=0.5, nu=0.3, heterogeneous=True,
                           seed=0)


def _cfg(variant, tmp_path=None, run_id="1", **solver_kw):
    solver_kw.setdefault("tol", 1e-8)
    solver_kw.setdefault("max_iter", 2000)
    cfg = RunConfig(
        solver=SolverConfig(pcg_variant=variant, **solver_kw),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                       export_flag=False),
    )
    cfg.run_id = run_id
    if tmp_path is not None:
        cfg.scratch_path = str(tmp_path)
    return cfg


def _iters_close(pipelined, classic):
    """Acceptance bar (ISSUE 11): pipelined iteration count within 5%
    of classic (+2 absolute slack for the one-trip lag on tiny
    counts)."""
    assert abs(pipelined - classic) <= max(2, int(0.05 * classic) + 1), \
        (pipelined, classic)


# ----------------------------------------------------------------------
# Convergence parity (golden + scipy)
# ----------------------------------------------------------------------

def test_pipelined_parity_direct_golden(model):
    """flag=0 on the golden heterogeneous cube, iteration count within
    5% of classic, identical solution to ~tol — the ISSUE-11 acceptance
    line."""
    rs = {}
    for variant in ("classic", "pipelined"):
        s = Solver(model, _cfg(variant), mesh=make_mesh(4), n_parts=4)
        rs[variant] = (s.step(1.0),
                       float(np.abs(s.displacement_global()).sum()))
    rc, cc = rs["classic"]
    rp, cp = rs["pipelined"]
    assert rc.flag == 0 and rp.flag == 0
    assert rp.relres <= 1e-8 * 1.001
    _iters_close(rp.iters, rc.iters)
    assert np.isclose(cp, cc, rtol=1e-6)


def test_pipelined_parity_mixed(model):
    """Mixed precision with pipelined f32 inner cycles: converges to
    the outer tolerance.  The f32 GV recurrence pays for its overlap
    with a lower attainable accuracy per cycle even under the
    PIPELINED_REPLACE_EVERY refresh (arXiv:2501.03743 §4), so the
    documented bound is ~1.35x classic's total inner iterations, not
    the direct path's 5% (docs/RUNBOOK.md: prefer classic/fused for
    mixed unless reduction latency dominates the iteration)."""
    rs = {}
    for variant in ("classic", "pipelined"):
        s = Solver(model, _cfg(variant, precision_mode="mixed"),
                   mesh=make_mesh(4), n_parts=4)
        rs[variant] = s.step(1.0)
    assert rs["classic"].flag == 0 and rs["pipelined"].flag == 0
    assert rs["pipelined"].relres <= 1e-8 * 1.001
    assert rs["pipelined"].iters <= 1.35 * rs["classic"].iters, \
        (rs["pipelined"].iters, rs["classic"].iters)


def test_pipelined_matches_scipy():
    from scipy.sparse.linalg import spsolve

    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    s = Solver(model, _cfg("pipelined"), mesh=make_mesh(1), n_parts=1)
    res = s.step(1.0)
    assert res.flag == 0
    K = model.assemble_csr()
    eff = model.dof_eff
    rhs = (model.F - K @ model.Ud)[eff]
    u_ref = np.array(model.Ud)
    u_ref[eff] += spsolve(K[eff][:, eff].tocsc(), rhs)
    np.testing.assert_allclose(s.displacement_global(), u_ref,
                               rtol=1e-5, atol=1e-8 * np.abs(u_ref).max())


def test_pipelined_trace_ring(model):
    """The in-graph convergence ring works unchanged under the
    pipelined body (one slot per resolved iteration; the priming trip
    writes none)."""
    s = Solver(model, _cfg("pipelined", trace_resid=64),
               mesh=make_mesh(1), n_parts=1)
    res = s.step(1.0)
    assert res.flag == 0
    tr = s.last_trace
    assert tr is not None and tr.n_recorded > 0
    assert tr.flag[-1] == 0
    assert tr.normr[-1] < tr.normr[0]


# ----------------------------------------------------------------------
# The overlap property on the REAL traced loop (tier-1 twin of the
# full-lint psum-overlap rule)
# ----------------------------------------------------------------------

_SETUP = {}


def _direct_pcg_setup(nx=5):
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
    from pcg_mpi_solver_tpu.parallel.partition import partition_model

    if nx in _SETUP:
        return _SETUP[nx]
    m = make_cube_model(nx, 4, 4, h=0.5, nu=0.3, load="traction",
                        heterogeneous=True)
    pm = partition_model(m, 1)
    data = device_data(pm, jnp.float64)
    ops = Ops.from_model(pm, dot_dtype=jnp.float64)
    eff = data["eff"]
    fext = eff * data["F"]
    d = eff * ops.diag(data)
    inv = jnp.where(d != 0, 1.0 / jnp.where(d != 0, d, 1.0), 0.0)
    _SETUP[nx] = (m, pm, ops, data, fext, inv)
    return _SETUP[nx]


def test_pipelined_body_psum_is_independent_of_the_stencil():
    """Trace the bare pipelined loop on a REAL 2-part partition and run
    the psum-overlap dependency analysis: exactly one fully
    data-independent psum (the (6,) fused reduction), while the same
    analysis on the fused loop shows zero — the latency-hiding claim,
    chipless, in tier-1."""
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.analysis import jaxpr_utils as ju
    from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
    from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS
    from pcg_mpi_solver_tpu.parallel.partition import partition_model
    from pcg_mpi_solver_tpu.solver.driver import _data_specs
    from pcg_mpi_solver_tpu.solver.pcg import pcg

    m = make_cube_model(3, 3, 3)
    pm = partition_model(m, 2)
    assert pm.n_iface > 0, "the claim needs the interface psum present"
    data = device_data(pm, jnp.float64)
    ops = Ops.from_model(pm, dot_dtype=jnp.float64, axis_name=PARTS_AXIS)
    mesh = make_mesh(2)
    P = jax.sharding.PartitionSpec(PARTS_AXIS)

    def trace_variant(variant):
        def step(data, fext, x0, inv_diag):
            res = pcg(ops, data, fext, x0, inv_diag, tol=1e-8,
                      max_iter=50, glob_n_dof_eff=pm.glob_n_dof_eff,
                      variant=variant)
            return res.x

        fn = jax.shard_map(step, mesh=mesh,
                           in_specs=(_data_specs(data), P, P, P),
                           out_specs=P, check_vma=False)
        z = jnp.zeros((pm.n_parts, pm.n_loc), jnp.float64)
        jx = jax.make_jaxpr(fn)(data, z, z, z)
        bodies = [ju.while_body(e) for e in ju.while_eqns(jx.jaxpr)
                  if ju.collective_histogram(ju.while_body(e))]
        assert len(bodies) == 1
        return ju.independent_collectives(bodies[0])

    indep_p = trace_variant("pipelined")
    assert len(indep_p) == 1 and indep_p[0]["primitive"] == "psum"
    assert indep_p[0]["out_size"] == 6          # the stacked reduction
    assert trace_variant("fused") == []          # serialized, as documented


# ----------------------------------------------------------------------
# Resumable dispatch: chunked bit-identity, kill-and-resume
# ----------------------------------------------------------------------

def test_pipelined_chunked_bit_identical_to_oneshot(model):
    """The GV recurrence state (u/w/s/q/z + init) rides the resumable
    carry, so capped pipelined dispatches are bit-identical to one long
    pipelined solve — including across the cold start's priming trip."""
    s1 = Solver(model, _cfg("pipelined"), mesh=make_mesh(4), n_parts=4)
    r1 = s1.step(1.0)
    s2 = Solver(model, _cfg("pipelined", iters_per_dispatch=12),
                mesh=make_mesh(4), n_parts=4)
    r2 = s2.step(1.0)
    assert r1.flag == r2.flag == 0
    assert r1.iters == r2.iters
    assert r1.relres == r2.relres
    np.testing.assert_array_equal(s1.displacement_global(),
                                  s2.displacement_global())


def test_pipelined_snapshot_kill_resume_bit_identity(model, tmp_path):
    """Mid-Krylov snapshot/resume round-trips the pipelined carry
    (incl. the four GV vectors and the priming bit): a kill at a chunk
    boundary plus --resume reproduces the uninterrupted solve
    bit-identically."""
    def cfg(run_id):
        c = _cfg("pipelined", tmp_path, run_id=run_id,
                 iters_per_dispatch=12)
        c.checkpoint_every = 1
        c.snapshot_every = 1
        return c

    sa = Solver(model, cfg("pa"), mesh=make_mesh(4), n_parts=4)
    sa.solve()
    ck = cfg("pk")
    sk = Solver(model, ck, mesh=make_mesh(4), n_parts=4)
    sk.fault_plan = FaultPlan("kill@2")
    with pytest.raises(SimulatedKill):
        sk.solve()
    sk2 = Solver(model, ck, mesh=make_mesh(4), n_parts=4)
    sk2.solve(resume=True)
    assert sk2.flags == sa.flags and sk2.iters == sa.iters
    assert sk2.relres == sa.relres
    np.testing.assert_array_equal(sk2.displacement_global(),
                                  sa.displacement_global())


@pytest.mark.parametrize("other", ["classic", "fused"])
def test_cross_variant_resume_rejected_by_fingerprint(model, tmp_path,
                                                      other):
    """A checkpoint written under pipelined must be rejected on resume
    under classic OR fused with a clear named mismatch — the pipelined
    carry rides five extra pytree leaves (u/w/s/z/init) beyond even
    fused's, so without the guard the failure would be an obscure
    shard_map structure error."""
    cfg_p = _cfg("pipelined", tmp_path, run_id=f"xv{other}",
                 iters_per_dispatch=12)
    cfg_p.checkpoint_every = 1
    s = Solver(model, cfg_p, mesh=make_mesh(1), n_parts=1)
    s.solve()

    cfg_o = _cfg(other, tmp_path, run_id=f"xv{other}",
                 iters_per_dispatch=12)
    cfg_o.checkpoint_every = 1
    s2 = Solver(model, cfg_o, mesh=make_mesh(1), n_parts=1)
    with pytest.raises(ValueError, match="pcg_variant"):
        s2.solve(resume=True)


# ----------------------------------------------------------------------
# Recovery-ladder compatibility (chaos: scalar path; the blocked matrix
# runs in test_pcg_many.test_chunked_column_fault_chaos_matrix)
# ----------------------------------------------------------------------

class _Capture:
    def __init__(self):
        self.events = []

    def emit(self, ev):
        self.events.append(ev)

    def close(self):
        pass


@pytest.mark.parametrize("fault,trigger", [
    ("rho0@1", "flag4"),       # zeroed rho => breakdown
    ("nan@1", "nan_carry"),    # NaN trips no in-graph flag; host detects
    # Inf residual: gamma = <r,u> sums signed infinities to NaN, which
    # (like every NaN) trips no in-graph flag — the host budget loop's
    # nan_carry detection hands it to the ladder
    ("inf@1", "nan_carry"),
])
def test_pipelined_fault_recovery(model, fault, trigger):
    """Breakdowns and NaN/Inf poisoning climb the same recovery ladder
    under the GV recurrence and still converge — the ladder's restart
    re-arms the priming bit, so the restarted solve rebuilds u/w from
    the restart residual."""
    cap = _Capture()
    s = Solver(model, _cfg("pipelined", iters_per_dispatch=12),
               mesh=make_mesh(1), n_parts=1,
               recorder=MetricsRecorder(sinks=[cap]))
    s.fault_plan = FaultPlan(fault, recorder=s.recorder)
    res = s.step(1.0)
    assert res.flag == 0 and res.relres <= 1e-8
    recs = [(e["action"], e["trigger"]) for e in cap.events
            if e["kind"] == "recovery"]
    assert ("restart_minres", trigger) in recs


def test_pipelined_mixed_escalates_to_f64(model):
    """Ladder rung 3 under pipelined: repeated mixed-path corruption
    escalates to direct-f64 cycles (themselves pipelined) and
    converges."""
    cap = _Capture()
    cfg = _cfg("pipelined", precision_mode="mixed", dtype="float32",
               dot_dtype="float64", tol=1e-9, max_iter=4000,
               inner_tol=0.1, max_recoveries=3, iters_per_dispatch=12)
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1,
               recorder=MetricsRecorder(sinks=[cap]))
    s.fault_plan = FaultPlan("inf@0,inf@1", recorder=s.recorder)
    res = s.step(1.0)
    assert res.flag == 0 and res.relres <= 1e-9
    recs = [(e["action"], e["trigger"]) for e in cap.events
            if e["kind"] == "recovery"]
    assert ("escalate_f64", "nan_carry") in recs


# ----------------------------------------------------------------------
# Residual-drift guard: the TIGHTER pipelined budget (flag 6)
# ----------------------------------------------------------------------

def test_pipelined_drift_guard_exits_flag6_at_the_lower_limit():
    """The pipelined recurrence drifts faster than fused
    (arXiv:2501.03743), so its flag-6 budget is LOWER
    (PIPELINED_DRIFT_LIMIT < FUSED_DRIFT_LIMIT): re-poisoning the carry
    residual before each capped dispatch makes every deferred check
    disagree, and the solve exits with the recoverable DRIFT_FLAG after
    exactly PIPELINED_DRIFT_LIMIT drifted checks."""
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.resilience import breakdown_trigger
    from pcg_mpi_solver_tpu.solver.pcg import (
        DRIFT_FLAG, FUSED_DRIFT_LIMIT, PIPELINED_DRIFT_LIMIT,
        drift_limit_for, pcg)

    assert PIPELINED_DRIFT_LIMIT < FUSED_DRIFT_LIMIT
    assert drift_limit_for("pipelined") == PIPELINED_DRIFT_LIMIT
    assert drift_limit_for("fused") == FUSED_DRIFT_LIMIT

    m, _pm, ops, data, fext, inv = _direct_pcg_setup()
    kw = dict(tol=1e-8, max_iter=1, max_iter_nominal=200,
              glob_n_dof_eff=int(np.asarray(m.dof_eff).sum()),
              variant="pipelined", return_carry=True)
    res, carry = pcg(ops, data, fext, jnp.zeros_like(fext), inv,
                     **dict(kw, max_iter=5))
    assert int(carry["drift"]) == 0, "healthy pipelined solve: no drift"
    assert int(carry["init"]) == 0, "the cold start primed u/w"
    step = jax.jit(lambda c: pcg(ops, data, fext, jnp.zeros_like(fext),
                                 inv, carry_in=c, **kw))
    for k in range(PIPELINED_DRIFT_LIMIT):
        # the recurrence claims convergence; the true residual disagrees
        carry = dict(carry)
        carry["r"] = carry["r"] * 1e-14
        res, carry = step(carry)
        assert int(carry["drift"]) == k + 1
    assert int(res.flag) == DRIFT_FLAG
    assert breakdown_trigger(int(res.flag), float(res.relres)) == "flag6"


def test_pipelined_drift_guard_per_column():
    """Blocked twin at the lower limit: only the lying column exits
    flag 6; the healthy column's drift count stays zero."""
    import jax
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.solver.pcg import (
        DRIFT_FLAG, PIPELINED_DRIFT_LIMIT, pcg_many)

    m, _pm, ops, data, fext1, inv = _direct_pcg_setup()
    fb = jnp.stack([fext1, 0.5 * fext1], axis=-1)
    kw = dict(tol=1e-8, max_iter=1, max_iter_nominal=200,
              glob_n_dof_eff=int(np.asarray(m.dof_eff).sum()),
              variant="pipelined", return_carry=True)
    res, carry = pcg_many(ops, data, fb, jnp.zeros_like(fb), inv,
                          **dict(kw, max_iter=5))
    lie = jnp.asarray([1e-14, 1.0])
    step = jax.jit(lambda c: pcg_many(ops, data, fb,
                                      jnp.zeros_like(fb), inv,
                                      carry_in=c, **kw))
    for _ in range(PIPELINED_DRIFT_LIMIT):
        carry = dict(carry)
        carry["r"] = carry["r"] * lie[None, None, :]
        res, carry = step(carry)
    assert int(res.flag[0]) == DRIFT_FLAG
    assert int(carry["drift"][0]) >= PIPELINED_DRIFT_LIMIT
    assert int(res.flag[1]) != DRIFT_FLAG
    assert int(carry["drift"][1]) == 0


def test_forced_checks_do_not_tick_the_progress_window():
    """A cadence-forced replacement check resolves no new committed
    iteration, so it must not advance the plateau/progress-window
    clocks (count_windows): with a huge progress_window (no rollover,
    no resets) the monotone win_count must equal the committed
    iteration count EXACTLY after crossing PIPELINED_REPLACE_EVERY —
    one tick per committed iteration, none for the forced check's
    extra _resolve — matching what classic would have counted."""
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.solver.pcg import (
        PIPELINED_REPLACE_EVERY, pcg)

    m, _pm, ops, data, fext, inv = _direct_pcg_setup()
    n_iter = PIPELINED_REPLACE_EVERY + 8    # crosses one forced cadence
    kw = dict(tol=1e-30, max_iter=n_iter, max_iter_nominal=200,
              glob_n_dof_eff=int(np.asarray(m.dof_eff).sum()),
              progress_window=10_000, return_carry=True)
    for variant in ("classic", "pipelined"):
        # tol is unreachable, so the loop runs exactly max_iter
        # committed iterations (cond: i < max_iter; checks/priming
        # trips do not advance i)
        _res, carry = pcg(ops, data, fext, jnp.zeros_like(fext), inv,
                          variant=variant, **kw)
        assert int(carry["win_count"]) == n_iter, \
            (variant, int(carry["win_count"]), n_iter)


# ----------------------------------------------------------------------
# Newmark per-step solves (the shifted-operator dispatch surface)
# ----------------------------------------------------------------------

def test_pipelined_newmark_steps_match_classic():
    from pcg_mpi_solver_tpu.solver.newmark import NewmarkSolver

    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, load="traction",
                            heterogeneous=True)
    us = {}
    for variant in ("classic", "pipelined"):
        s = NewmarkSolver(model, _cfg(variant), mesh=make_mesh(1),
                          n_parts=1, dt=1e-5)
        res = s.run([1.0, 1.0, 1.0])
        assert all(r.flag == 0 for r in res), variant
        us[variant] = s.displacement_global()
    np.testing.assert_allclose(us["pipelined"], us["classic"], rtol=1e-5,
                               atol=1e-10 * np.abs(us["classic"]).max())


# ----------------------------------------------------------------------
# MG composition (ISSUE 11 acceptance: pipelined under precond="mg")
# ----------------------------------------------------------------------

def test_pipelined_composes_with_mg():
    """precond='mg' under the pipelined loop: identical-tol convergence
    with iteration count within 5% of classic+mg (the multiplicative
    fewer-iterations x cheaper-iterations composition), and the
    V-cycle's collectives all land on the carry side of the overlap —
    the fused psum stays independent."""
    m = make_cube_model(6, 4, 4, h=0.5, nu=0.3, heterogeneous=True,
                        seed=0)
    rs = {}
    for variant in ("classic", "pipelined"):
        s = Solver(m, _cfg(variant, precond="mg"),
                   mesh=make_mesh(2), n_parts=2, backend="general")
        rs[variant] = (s.step(1.0),
                       np.asarray(s.displacement_global()))
    rc, uc = rs["classic"]
    rp, up = rs["pipelined"]
    assert rc.flag == 0 and rp.flag == 0
    assert rp.relres <= 1e-8 * 1.001
    _iters_close(rp.iters, rc.iters)
    np.testing.assert_allclose(up, uc, rtol=1e-6,
                               atol=1e-10 * np.abs(uc).max())


# ----------------------------------------------------------------------
# Single-source variant table + plumbing surfaces (ISSUE 11 satellite)
# ----------------------------------------------------------------------

def test_variant_name_set_is_single_sourced():
    """config.PCG_VARIANTS is THE name set: the solver's valid list,
    the ops collective table and the CLI choices all derive from it."""
    from pcg_mpi_solver_tpu.obs.schema import BENCH_PCG_VARIANT_VALUES
    from pcg_mpi_solver_tpu.ops.matvec import PCG_SCALAR_PSUMS
    from pcg_mpi_solver_tpu.solver.pcg import VALID_PCG_VARIANTS

    assert VALID_PCG_VARIANTS == PCG_VARIANTS
    assert tuple(PCG_SCALAR_PSUMS) == PCG_VARIANTS
    assert BENCH_PCG_VARIANT_VALUES == PCG_VARIANTS
    assert "pipelined" in PCG_VARIANTS


def test_unknown_variant_fails_loudly_everywhere(model):
    """The same unknown name is rejected by every surface: config
    construction, the cache key, and the loop builders."""
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.cache.keys import step_cache_key
    from pcg_mpi_solver_tpu.solver.pcg import pcg, pcg_many

    with pytest.raises(ValueError, match="pcg_variant"):
        SolverConfig(pcg_variant="frobnicate")
    with pytest.raises(KeyError, match="pcg_variant"):
        step_cache_key(abstract="a", mesh="m", backend="b", solver={},
                       trace_len=0, glob_n_dof_eff=1, donate=True,
                       jax_version="j", pcg_variant="frobnicate")
    for fn in (pcg, pcg_many):
        with pytest.raises(ValueError, match="variant"):
            fn(None, None, jnp.zeros((1, 3)), jnp.zeros((1, 3)),
               jnp.ones((1, 3)), tol=1e-8, max_iter=5,
               glob_n_dof_eff=3, variant="frobnicate")


def test_cache_key_separates_pipelined():
    from pcg_mpi_solver_tpu.cache.keys import step_cache_key

    kw = dict(abstract="sig", mesh=("m", "cpu"), backend="general",
              solver={"tol": 1e-8}, trace_len=0, glob_n_dof_eff=100,
              donate=True, jax_version="x")
    keys = {v: step_cache_key(pcg_variant=v, **kw) for v in PCG_VARIANTS}
    assert len(set(keys.values())) == len(PCG_VARIANTS)


def test_cli_flag_accepts_pipelined():
    import argparse

    from pcg_mpi_solver_tpu.cli import _add_variant_flag, _load_settings
    from types import SimpleNamespace

    p = argparse.ArgumentParser()
    _add_variant_flag(p)
    args = p.parse_args(["--pcg-variant", "pipelined"])
    assert args.pcg_variant == "pipelined"
    with pytest.raises(SystemExit):
        p.parse_args(["--pcg-variant", "frobnicate"])

    ns = SimpleNamespace(settings=None, tol=None, max_iter=None,
                         precision=None, precond=None,
                         pcg_variant="pipelined")
    assert _load_settings(None, ns).solver.pcg_variant == "pipelined"


def test_comm_gauges_advertise_pipelined(model):
    cap = _Capture()
    s = Solver(model, _cfg("pipelined"), mesh=make_mesh(1), n_parts=1,
               recorder=MetricsRecorder(sinks=[cap]))
    s.step(1.0)
    s.recorder.emit_run_summary()
    summaries = [e for e in cap.events if e["kind"] == "run_summary"]
    assert summaries
    g = summaries[-1]["gauges"]
    assert g["pcg_variant"] == "pipelined"
    assert g["comm.pcg_variant"] == "pipelined"
    # same psum COUNT as fused — the pipelined win is overlap, not count
    assert g["comm.psums_per_iter"] == \
        s.ops.comm_estimate(variant="fused")["psums_per_iter"]


def test_bench_line_validates_pipelined_variant():
    from pcg_mpi_solver_tpu.obs.schema import validate_bench_line

    line = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
            "detail": {"pcg_variant": "pipelined", "time_to_tol_s": 0.5,
                       "iters": 10}}
    assert validate_bench_line(line) == []
    line["detail"]["pcg_variant"] = "frobnicate"
    errs = validate_bench_line(line)
    assert errs and "pcg_variant" in errs[0]


def test_pipelined_carry_exports_gv_leaves():
    """The resumable carry of a pipelined call exports the recurrence
    vectors and the priming bit (the contract every dispatch surface,
    snapshot and restart program relies on)."""
    import jax.numpy as jnp

    from pcg_mpi_solver_tpu.solver.pcg import (
        carry_part_specs, cold_carry, cold_carry_many, pcg)

    m, _pm, ops, data, fext, inv = _direct_pcg_setup()
    _res, carry = pcg(ops, data, fext, jnp.zeros_like(fext), inv,
                      tol=1e-8, max_iter=3,
                      glob_n_dof_eff=int(np.asarray(m.dof_eff).sum()),
                      variant="pipelined", return_carry=True)
    for k in ("u", "w", "s", "q", "z", "alpha", "fresh", "drift",
              "init"):
        assert k in carry, k
    # cold_carry / specs agree with the exported schema
    cold = cold_carry(jnp.zeros_like(fext), fext,
                      jnp.asarray(1.0), jnp.float64, variant="pipelined")
    assert set(cold) == set(carry)
    specs = carry_part_specs("P", "R", variant="pipelined")
    assert set(specs) == set(carry)
    cold_m = cold_carry_many(jnp.zeros((1, 3, 2)), jnp.zeros((1, 3, 2)),
                             jnp.ones((2,)), jnp.float64,
                             variant="pipelined")
    for k in ("u", "w", "s", "z", "init", "flag", "prec_sel"):
        assert k in cold_m, k
