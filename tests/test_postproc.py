"""Quadrature tables + crack-tip / time-history post-processing tests
(reference file_operations.py:177-247, 542-787)."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.utils.io import RunStore
from pcg_mpi_solver_tpu.utils.postproc import (
    calc_crack_tip_velocity,
    crack_length_and_velocity,
    crack_tip_history,
    find_nodes_at,
    get_time_history_data,
    smooth_moving_average,
)
from pcg_mpi_solver_tpu.utils.quadrature import (
    gauss_lobatto_table,
    gauss_points_3d,
    gauss_table,
)


# ---------------------------------------------------------------- quadrature
def test_gauss_tables_match_reference_closed_forms():
    """file_operations.py:179-211 hardcodes these values for 1-4 points."""
    cases = {
        1: ([0.0], [2.0]),
        2: ([-1 / 3**0.5, 1 / 3**0.5], [1.0, 1.0]),
        3: ([-(3 / 5)**0.5, 0.0, (3 / 5)**0.5], [5 / 9, 8 / 9, 5 / 9]),
        4: ([-(3/7 + (2/7) * (6/5)**0.5)**0.5, -(3/7 - (2/7) * (6/5)**0.5)**0.5,
             (3/7 - (2/7) * (6/5)**0.5)**0.5, (3/7 + (2/7) * (6/5)**0.5)**0.5],
            [(18 - 30**0.5) / 36, (18 + 30**0.5) / 36,
             (18 + 30**0.5) / 36, (18 - 30**0.5) / 36]),
    }
    for n, (ni_ref, wi_ref) in cases.items():
        ni, wi = gauss_table(n)
        np.testing.assert_allclose(ni, np.sort(ni_ref), rtol=1e-14, atol=1e-14)
        order = np.argsort(ni_ref)
        np.testing.assert_allclose(wi, np.asarray(wi_ref)[order], rtol=1e-14)


def test_gauss_lobatto_matches_reference_closed_forms():
    """file_operations.py:222-241."""
    cases = {
        2: ([-1.0, 1.0], [1.0, 1.0]),
        3: ([-1.0, 0.0, 1.0], [1 / 3, 4 / 3, 1 / 3]),
        4: ([-1.0, -1 / 5**0.5, 1 / 5**0.5, 1.0], [1 / 6, 5 / 6, 5 / 6, 1 / 6]),
        5: ([-1.0, -(3 / 7)**0.5, 0.0, (3 / 7)**0.5, 1.0],
            [1 / 10, 49 / 90, 32 / 45, 49 / 90, 1 / 10]),
    }
    for n, (ni_ref, wi_ref) in cases.items():
        ni, wi = gauss_lobatto_table(n)
        np.testing.assert_allclose(ni, ni_ref, rtol=1e-13, atol=1e-13)
        np.testing.assert_allclose(wi, wi_ref, rtol=1e-13)


def test_gauss_polynomial_exactness():
    for n in (2, 3, 5, 8):
        ni, wi = gauss_table(n)
        for deg in range(2 * n):       # exact through degree 2n-1
            exact = (1 - (-1) ** (deg + 1)) / (deg + 1)
            np.testing.assert_allclose(np.sum(wi * ni**deg), exact,
                                       rtol=1e-12, atol=1e-13)


def test_gauss_points_3d_integrates_volume():
    pts, w = gauss_points_3d(2)
    assert pts.shape == (8, 3) and w.shape == (8,)
    np.testing.assert_allclose(w.sum(), 8.0, rtol=1e-14)   # volume of [-1,1]^3
    # exact for x^2 y^2 z^2: (2/3)^3
    np.testing.assert_allclose(np.sum(w * np.prod(pts**2, axis=1)),
                               (2 / 3) ** 3, rtol=1e-12)


# ------------------------------------------------------------- postprocessing
def test_smooth_moving_average_reference_semantics():
    rng = np.random.default_rng(0)
    x = rng.normal(size=40)
    so = 3
    # reference oracle: two explicit passes, zero edges
    # (file_operations.py:581-590)
    a = x.copy()
    for _ in range(2):
        b = np.zeros_like(a)
        for q in range(so, len(a) - so):
            b[q] = np.mean(a[q - so:q + so + 1])
        a = b
    np.testing.assert_allclose(smooth_moving_average(x, so, passes=2), a,
                               rtol=1e-13)
    assert np.all(smooth_moving_average(x, so)[:so] == 0)


@pytest.fixture()
def crack_run(tmp_path):
    """Synthetic run: a damage front advancing along +x at constant speed."""
    model = make_cube_model(10, 3, 3, h=1.0)
    store = RunStore(str(tmp_path / "run"), "m")
    store.prepare()
    node_map = np.arange(model.n_node)
    store.write_map("NodeId", node_map)
    store.write_map("Dof", np.arange(model.n_dof))
    speed, dt, n_frames = 2.0, 0.25, 20
    x = model.node_coords[:, 0]
    for k in range(n_frames):
        D = (x <= speed * dt * k).astype(float)
        store.write_frame("D", k, D)
        store.write_frame("U", k, np.full(model.n_dof, 0.1 * k))
        store.write_frame("PS1", k, x * k)
    store.write_time_list(dt * np.arange(n_frames))
    return model, store, speed, dt, n_frames


def test_crack_tip_history_and_velocity(crack_run):
    model, store, speed, dt, n_frames = crack_run
    tips = crack_tip_history(store, model)
    assert tips.shape == (n_frames, 3)
    # tip x advances at `speed` wherever the front is inside the block
    interior = (tips[:, 0] > 0) & (tips[:, 0] < 10)
    assert np.any(interior)
    times = store.read_time_list()
    crk_len, vel = crack_length_and_velocity(times, tips)
    assert np.all(np.diff(crk_len) >= 0)
    mid = np.where(interior)[0][1:-1]
    np.testing.assert_allclose(vel[mid], speed, rtol=1e-10)


def test_calc_crack_tip_velocity_saves(crack_run):
    model, store, *_ = crack_run
    out = calc_crack_tip_velocity(store, model, smooth_half_window=2,
                                  drop_last=2)
    assert set(out) == {"CTVel", "DmgNodeCoord", "CrkLen", "Time_T"}
    import os

    assert os.path.exists(f"{store.result_path}/CrackTipVelData.npy")


def test_get_time_history_data(crack_run):
    model, store, *_ = crack_run
    coords = model.node_coords[[0, 5]]
    out = get_time_history_data(store, model, coords, nodal_vars=("PS1",))
    n_frames = len(store.read_time_list())
    assert out["U"].shape == (n_frames, 2)
    np.testing.assert_allclose(out["U"][:, 0], 0.1 * np.arange(n_frames))
    np.testing.assert_allclose(out["PS1"][:, 1],
                               model.node_coords[5, 0] * np.arange(n_frames))
    import os

    assert os.path.exists(f"{store.result_path}/TimeHistoryData.mat")
    with pytest.raises(ValueError):
        find_nodes_at(model, np.array([[123.4, 0, 0]]))
