"""Multi-host (DCN) path: helpers single-process, plus REAL two- and
four-process jax.distributed runs of the full solver over a split CPU mesh
— the framework's analogue of the reference's multi-node mpiexec runs
(which the reference itself never tests without a cluster; SURVEY.md
§4.5)."""

import os
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

from pcg_mpi_solver_tpu.parallel import make_mesh
from pcg_mpi_solver_tpu.parallel.distributed import (
    init_distributed, make_global_mesh, put_sharded, put_tree)
from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS


def test_single_process_no_op_init():
    assert init_distributed() == 0
    assert jax.process_count() == 1


def test_make_global_mesh():
    mesh = make_global_mesh()
    assert mesh.devices.size == 8
    assert mesh.axis_names == (PARTS_AXIS,)
    assert make_global_mesh(4).devices.size == 4


def test_put_sharded_matches_device_put():
    mesh = make_mesh(8)
    spec = jax.sharding.PartitionSpec(PARTS_AXIS)
    x = np.arange(8 * 6, dtype=np.float64).reshape(8, 6)
    a = put_sharded(x, mesh, spec)
    np.testing.assert_array_equal(np.asarray(a), x)
    assert a.sharding.spec == spec


def test_put_tree_handles_nested_and_none():
    mesh = make_mesh(8)
    P = jax.sharding.PartitionSpec
    tree = {"a": np.ones((8, 4)), "b": [np.zeros((8, 2)), None],
            "c": np.ones((3, 3))}
    specs = {"a": P(PARTS_AXIS), "b": [P(PARTS_AXIS), P(PARTS_AXIS)],
             "c": P()}
    out = put_tree(tree, mesh, specs)
    np.testing.assert_array_equal(np.asarray(out["a"]), tree["a"])
    assert out["b"][1] is None
    assert out["c"].sharding.spec == P()


def make_mh_test_model(backend):
    """The multi-process test model — ONE definition, embedded into the
    child script via getsource so the reference solve and the child can
    never drift apart."""
    if backend == "hybrid":
        from pcg_mpi_solver_tpu.models.octree import make_octree_model

        return make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3)
    from pcg_mpi_solver_tpu.models import make_cube_model

    if backend == "structured":
        # slab decomposition needs nx % n_parts == 0 (8 parts)
        return make_cube_model(8, 4, 4, heterogeneous=True)
    return make_cube_model(6, 4, 4, heterogeneous=True)


_CHILD = r"""
import os, sys
N_PROCS = int(sys.argv[4])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={8 // N_PROCS}")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from pcg_mpi_solver_tpu.parallel.distributed import (
    init_distributed, make_global_mesh)

pid = init_distributed(coordinator_address=sys.argv[1],
                       num_processes=N_PROCS, process_id=int(sys.argv[2]))
assert jax.process_count() == N_PROCS, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.solver import Solver
from pcg_mpi_solver_tpu.utils.io import RunStore

# Exports + checkpointing ON: every process computes (collective fetches),
# only process 0 writes (multi-host-safe write gating).
scratch = sys.argv[3]
BACKEND = sys.argv[5]
model = make_mh_test_model(BACKEND)
cfg = RunConfig(scratch_path=scratch, run_id="mh", checkpoint_every=1,
                solver=SolverConfig(tol=1e-8, max_iter=500),
                time_history=TimeHistoryConfig(
                    time_step_delta=[0.0, 0.5, 1.0],
                    export_flag=True, export_frame_rate=1,
                    plot_flag=True, probe_dofs=(3, 10)))
s = Solver(model, cfg, mesh=make_global_mesh(), n_parts=8, backend=BACKEND)
assert s.backend == BACKEND, s.backend
store = RunStore(cfg.result_path)
res = s.solve(store=store)[-1]
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("exports_flushed")
import glob as _glob
n_frames = store.n_frames("U")
n_shards = len(_glob.glob(os.path.join(cfg.result_path, "ResVecData",
                                       "U_0.part*.npy")))
n_ckpts = len(_glob.glob(os.path.join(cfg.checkpoint_path, "ckpt_*.npz")))
print(f"RESULT {pid} flag={res.flag} iters={res.iters} relres={res.relres:.6e}",
      flush=True)
print(f"FILES {pid} primary={store.primary} frames={n_frames} ckpts={n_ckpts}",
      flush=True)
assert res.flag == 0
assert store.primary == (pid == 0)
# Parallel I/O: every process wrote its own part-range shard
assert n_shards == N_PROCS, n_shards
assert n_frames == 3, n_frames       # steps 0, 1, 2 at frame_rate 1
# reassembled frame == collective (all-gather) owner-masked payload
import numpy as _np
_np.testing.assert_array_equal(store.read_frame("U", 2),
                               s.displacement_owned())
if pid == 0:
    assert n_ckpts == 2, n_ckpts     # steps 1, 2
"""


def _run_multiproc(tmp_path, child_source, n_procs, extra_argv):
    """Launch n_procs jax.distributed children of ``child_source`` (argv:
    coordinator, process id, *extra_argv) and return their RESULT lines.
    Children are killed on timeout so a hung collective cannot leak
    processes past the test."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    import inspect

    script = tmp_path / "child.py"
    script.write_text(inspect.getsource(make_mh_test_model) + child_source)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    procs = [subprocess.Popen(
                 [sys.executable, str(script), coord, str(i)] + extra_argv,
                 stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                 text=True, env=env)
             for i in range(n_procs)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
    results = [l for out in outs for l in out.splitlines()
               if l.startswith("RESULT")]
    assert len(results) == n_procs
    return results


@pytest.mark.skipif(os.environ.get("PCG_TPU_SKIP_MULTIPROC") == "1",
                    reason="multi-process test disabled")
@pytest.mark.parametrize("n_procs,backend", [(2, "general"), (4, "general"),
                                             (2, "hybrid"),
                                             (2, "structured")])
def test_multi_process_solve(tmp_path, n_procs, backend):
    scratch = tmp_path / "scratch"
    results = _run_multiproc(tmp_path, _CHILD, n_procs,
                             [str(scratch), str(n_procs), backend])
    # both controllers observed the identical converged state
    for r in results[1:]:
        assert r.split(" ", 2)[2] == results[0].split(" ", 2)[2]

    # and it matches a single-process 8-part solve
    iters_multi = int(results[0].split("iters=")[1].split()[0])
    assert abs(_reference_iters(backend) - iters_multi) <= 1


_CHILD_NEWMARK = r"""
import os, sys
N_PROCS = int(sys.argv[3])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={8 // N_PROCS}")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from pcg_mpi_solver_tpu.parallel.distributed import (
    init_distributed, make_global_mesh)

pid = init_distributed(coordinator_address=sys.argv[1],
                       num_processes=N_PROCS, process_id=int(sys.argv[2]))
assert jax.process_count() == N_PROCS, jax.process_count()
assert jax.device_count() == 8, jax.device_count()

import numpy as np
from pcg_mpi_solver_tpu import RunConfig, SolverConfig
from pcg_mpi_solver_tpu.solver import NewmarkSolver

model = make_mh_test_model("general")
cfg = RunConfig(solver=SolverConfig(tol=1e-10, max_iter=1000,
                                    precond="block3"))
nm = NewmarkSolver(model, cfg, mesh=make_global_mesh(), n_parts=8,
                   dt=0.2, damping=0.1)
res = nm.run([0.5, 1.0, 1.0])
u = nm.state_global()[0]          # collective fetch on every process
cs = float(np.abs(u).sum())
print(f"RESULT {pid} flags={[r.flag for r in res]} "
      f"iters={[r.iters for r in res]} cs={cs:.12e}", flush=True)
assert all(r.flag == 0 for r in res)
"""


@pytest.mark.skipif(os.environ.get("PCG_TPU_SKIP_MULTIPROC") == "1",
                    reason="multi-process test disabled")
def test_multi_process_newmark(tmp_path):
    """Implicit Newmark (block3 precond) under REAL 2-process
    jax.distributed: both controllers integrate the same trajectory, and
    it matches a single-process 8-part run."""
    results = _run_multiproc(tmp_path, _CHILD_NEWMARK, 2, ["2"])
    assert results[0].split(" ", 2)[2] == results[1].split(" ", 2)[2]

    # single-process 8-part reference trajectory
    from pcg_mpi_solver_tpu import RunConfig, SolverConfig
    from pcg_mpi_solver_tpu.solver import NewmarkSolver

    model = make_mh_test_model("general")
    cfg = RunConfig(solver=SolverConfig(tol=1e-10, max_iter=1000,
                                        precond="block3"))
    nm = NewmarkSolver(model, cfg, mesh=make_mesh(8), n_parts=8,
                       dt=0.2, damping=0.1)
    nm.run([0.5, 1.0, 1.0])
    cs_ref = float(np.abs(nm.state_global()[0]).sum())
    cs_multi = float(results[0].split("cs=")[1])
    assert np.isclose(cs_multi, cs_ref, rtol=1e-9), (cs_multi, cs_ref)


_REF_ITERS = {}


def _reference_iters(backend: str) -> int:
    """Single-process 8-part reference solve (computed once per backend;
    all n_procs parametrizations compare against the same number)."""
    if backend not in _REF_ITERS:
        from pcg_mpi_solver_tpu import (RunConfig, SolverConfig,
                                        TimeHistoryConfig)
        from pcg_mpi_solver_tpu.solver import Solver

        model = make_mh_test_model(backend)
        cfg = RunConfig(solver=SolverConfig(tol=1e-8, max_iter=500),
                        time_history=TimeHistoryConfig(
                            time_step_delta=[0.0, 0.5, 1.0],
                            export_flag=False))
        s1 = Solver(model, cfg, mesh=make_mesh(8), n_parts=8,
                    backend=backend)
        _REF_ITERS[backend] = s1.solve()[-1].iters
    return _REF_ITERS[backend]
