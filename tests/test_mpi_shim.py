"""Unit coverage for the multi-rank mpi4py shim (tools/mpi_shim) — the
transport under the reference-oracle tests.  Exercises every primitive
the reference calls, at 4 real processes: collectives (allreduce/gather/
scatter/bcast/Allgather), tagged Isend/Recv rings, object isend/recv,
contiguous shared-memory windows with both Shared_query idioms, and
concurrent MPI-IO at disjoint offsets."""

import os
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

RANK_PROGRAM = textwrap.dedent("""
    import os
    import numpy as np
    from mpi4py import MPI

    comm = MPI.COMM_WORLD
    rank, size = comm.Get_rank(), comm.Get_size()
    assert size == 4

    # collectives
    assert comm.allreduce(rank + 1, op=MPI.SUM) == 10
    arr = comm.allreduce(np.array([rank, 1.0]), op=MPI.SUM)
    assert arr[0] == 6 and arr[1] == 4
    g = comm.gather(rank * 10, root=0)
    if rank == 0:
        assert g == [0, 10, 20, 30], g
        sc = comm.scatter([x * 2 for x in range(4)], root=0)
    else:
        assert g is None
        sc = comm.scatter(None, root=0)
    assert sc == rank * 2
    assert comm.bcast({"v": 42} if rank == 0 else None, root=0)["v"] == 42
    recvbuf = np.zeros((4, 3))
    comm.Allgather(np.array([rank] * 3, dtype=float), recvbuf)
    assert (recvbuf == np.arange(4)[:, None]).all()

    # p2p ring with the reference's tag discipline (send tag = my rank,
    # recv tag = source rank — pcg_solver.py:321,326)
    right, left = (rank + 1) % size, (rank - 1) % size
    req = comm.Isend(np.full(5, rank, dtype=np.int64), dest=right, tag=rank)
    got = np.zeros(5, dtype=np.int64)
    comm.Recv(got, source=left, tag=left)
    MPI.Request.Waitall([req])
    assert (got == left).all()
    comm.isend({"from": rank}, dest=right, tag=100 + rank)
    assert comm.recv(source=left, tag=100 + left)["from"] == left

    # shared window, LoadingRank pattern (file_operations.py:306-339)
    shared = comm.Split_type(MPI.COMM_TYPE_SHARED)
    nb = 8 * 16 if shared.Get_rank() == 1 else 0
    win = MPI.Win.Allocate_shared(nb, 8, comm=shared)
    buf, item = win.Shared_query(1)
    a = np.ndarray(buffer=buf, dtype=np.float64, shape=(16,))
    if shared.Get_rank() == 1:
        a[:] = np.arange(16) * 3.5
    shared.barrier()
    assert (a == np.arange(16) * 3.5).all()
    buf0, _ = win.Shared_query(0)   # query(0) = same base (zero-size ranks)
    assert (np.ndarray(buffer=buf0, dtype=np.float64, shape=(16,)) == a).all()

    # MPI-IO: disjoint offset writes, then read-all
    fname = os.path.join(os.environ["MPI_SHIM_JOBDIR"], "io.bin")
    fh = MPI.File.Open(comm, fname, MPI.MODE_WRONLY | MPI.MODE_CREATE)
    fh.Write_at(rank * 32, np.full(4, rank, dtype=np.float64))
    fh.Close()
    comm.barrier()
    fh = MPI.File.Open(comm, fname, MPI.MODE_RDONLY)
    out = np.zeros(16)
    fh.Read_at(0, out)
    fh.Close()
    assert (out.reshape(4, 4) == np.arange(4)[:, None]).all()
    comm.barrier()
    print(f"rank {rank}: ALL OK")
""")


def test_multirank_primitives(tmp_path):
    if TOOLS not in sys.path:
        sys.path.insert(0, TOOLS)
    from mpi_shim.mpiexec import launch

    prog = tmp_path / "rank_program.py"
    prog.write_text(RANK_PROGRAM)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    rc, outputs = launch([sys.executable, str(prog)], ranks=4, env=env,
                         timeout=180)
    assert rc == 0, "\n".join(outputs)
    for r, out in enumerate(outputs):
        assert f"rank {r}: ALL OK" in out, out


def test_rank_failure_terminates_job(tmp_path):
    """One failing rank must fail the whole launch (and not hang)."""
    if TOOLS not in sys.path:
        sys.path.insert(0, TOOLS)
    from mpi_shim.mpiexec import launch

    prog = tmp_path / "boom.py"
    prog.write_text(textwrap.dedent("""
        import sys, time
        from mpi4py import MPI
        comm = MPI.COMM_WORLD
        if comm.Get_rank() == 2:
            sys.exit(7)
        time.sleep(60)   # survivors would hang without fail-fast
    """))
    rc, _ = launch([sys.executable, str(prog)], ranks=4, timeout=120)
    assert rc != 0


def test_single_rank_unchanged():
    """Without MPI_SHIM_SIZE the shim stays the in-process single-rank
    transport (the baseline-measurement path must not regress)."""
    import subprocess

    shim = os.path.join(TOOLS, "mpi_shim")
    env = dict(os.environ, PYTHONPATH=shim)
    env.pop("MPI_SHIM_SIZE", None)
    proc = subprocess.run(
        [sys.executable, "-c",
         "from mpi4py import MPI\n"
         "c = MPI.COMM_WORLD\n"
         "assert c.Get_size() == 1 and c.Get_rank() == 0\n"
         "assert c.allreduce(3, op=MPI.SUM) == 3\n"
         "print('single-rank ok')"],
        env=env, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "single-rank ok" in proc.stdout
