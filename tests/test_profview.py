"""Profile harvest (ISSUE 15, obs/profview.py + obs/trend.py): the
capture -> parse -> attribute round trip on CPU, the tolerant-reader
degradation legs, the overlap interval math on synthetic timelines, and
the bench-trend regression sentinel over the committed BENCH_r0*.json
artifacts."""

import glob
import gzip
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig  # noqa: E402
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model  # noqa: E402
from pcg_mpi_solver_tpu.obs import profview, trend  # noqa: E402
from pcg_mpi_solver_tpu.obs.schema import validate_event  # noqa: E402
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh  # noqa: E402
from pcg_mpi_solver_tpu.solver.driver import Solver  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _solver(nx=6, n_parts=1, variant="classic", max_iter=300):
    cfg = RunConfig(solver=SolverConfig(tol=1e-8, max_iter=max_iter,
                                        pcg_variant=variant))
    model = make_cube_model(nx, nx, nx, heterogeneous=True)
    return Solver(model, cfg, mesh=make_mesh(n_parts), n_parts=n_parts,
                  backend="general")


class _CapturingRecorder:
    """Minimal recorder double: records events/gauges for assertions."""

    def __init__(self):
        self.events = []
        self.gauges = {}

    def event(self, kind, **fields):
        ev = {"schema": "pcg-tpu-telemetry/1", "t": 0.0, "kind": kind}
        ev.update(fields)
        self.events.append(ev)
        return ev

    def gauge(self, name, value):
        self.gauges[name] = value


# ----------------------------------------------------------------------
# overlap interval math on synthetic timelines
# ----------------------------------------------------------------------

def test_merge_and_intersect_interval_math():
    merged = profview.merge_intervals([(5, 7), (0, 2), (1, 3), (9, 9)])
    assert merged == [(0, 3), (5, 7)]
    assert profview.intersect_len((0, 10), merged) == 5.0
    assert profview.intersect_len((3, 5), merged) == 0.0
    assert profview.intersect_len((2.5, 6), merged) == 1.5


def _op(name, ts, dur, pid=1, tid=1, text=""):
    return {"name": name, "base": profview._base_name(name), "ts": ts,
            "dur": dur, "pid": pid, "tid": tid, "text": text}


def test_overlap_disjoint_contained_partial_spans():
    # disjoint: the collective and all compute never coincide -> 0
    ops = [_op("all-reduce.0", 0, 10, tid=1),
           _op("dot.1", 20, 10, tid=2)]
    assert profview.collective_overlap(ops)["overlap_frac"] == 0.0
    # contained: compute fully covers the collective -> 1
    ops = [_op("all-reduce.0", 5, 10, tid=1),
           _op("dot.1", 0, 30, tid=2)]
    assert profview.collective_overlap(ops)["overlap_frac"] == 1.0
    # partial: half the collective span is covered -> 0.5
    ops = [_op("all-reduce.0", 0, 10, tid=1),
           _op("dot.1", 5, 20, tid=2)]
    r = profview.collective_overlap(ops)
    assert r["overlap_frac"] == pytest.approx(0.5)
    assert r["n_collectives"] == 1 and r["coll_us"] == 10.0


def test_overlap_excludes_same_thread_and_other_lanes():
    # same tid = serialized by construction (and a parent span would
    # fake 100%): contributes nothing
    ops = [_op("all-reduce.0", 0, 10, tid=1),
           _op("dot.1", 0, 10, tid=1)]
    assert profview.collective_overlap(ops)["overlap_frac"] == 0.0
    # a different pid is a different device lane: also excluded
    ops = [_op("all-reduce.0", 0, 10, pid=1, tid=1),
           _op("dot.1", 0, 10, pid=2, tid=2)]
    assert profview.collective_overlap(ops)["overlap_frac"] == 0.0
    # no collectives at all -> frac is None (n/a), not 0 (a
    # single-device capture must not read as "proven serialized")
    ops = [_op("dot.1", 0, 10)]
    assert profview.collective_overlap(ops)["overlap_frac"] is None


def test_overlap_merges_overlapping_compute_spans():
    # two compute spans covering the same window must not double-count
    ops = [_op("all-reduce.0", 0, 10, tid=1),
           _op("dot.1", 0, 8, tid=2),
           _op("fusion.2", 2, 6, tid=3)]
    r = profview.collective_overlap(ops)
    assert r["overlap_us"] == pytest.approx(8.0)


# ----------------------------------------------------------------------
# bucketing: labels, scope map, unknown counting
# ----------------------------------------------------------------------

def test_bucket_phases_via_text_labels_and_scope_map():
    scope_map = {"dot.7": "matvec", "reduce.3": "reduction"}
    ops = [
        # TPU flavor: the label rides the event metadata text
        _op("fusion.1", 0, 4, text="jit(f)/pcg/axpy/add"),
        # CPU flavor: bare instruction name through the sidecar map
        _op("dot.7", 0, 10),
        # base-name fallback (different lowering suffix)
        _op("reduce.9", 0, 2),
        # no phase anywhere -> other
        _op("copy.5", 0, 3),
    ]
    b = profview.bucket_phases(ops, scope_map)
    assert b["phases"]["axpy"]["us"] == 4.0
    assert b["phases"]["matvec"]["us"] == 10.0
    assert b["phases"]["reduction"]["us"] == 2.0
    assert b["other_us"] == 3.0 and b["other_events"] == 1
    # nothing dropped: bucketed + other == total
    total = sum(d["us"] for d in b["phases"].values()) + b["other_us"]
    assert total == pytest.approx(sum(o["dur"] for o in ops))


def test_bucket_phases_counts_unknown_scope_labels():
    ops = [_op("dot.1", 0, 5, text="jit(f)/pcg/halo/op"),
           _op("dot.2", 0, 5, text="jit(f)/pcg/halo/op2")]
    b = profview.bucket_phases(ops, {})
    assert b["unknown_scopes"] == {"halo": 2}
    assert b["other_events"] == 2          # counted, not dropped


def test_ambiguous_base_name_never_guesses():
    # two instructions share a base but bucket to different phases:
    # the base fallback must refuse, not pick one
    scope_map = {"fusion.1": "matvec", "fusion.2": "axpy"}
    bm = profview._base_scope_map(scope_map)
    assert bm["fusion"] is None
    assert profview.phase_of(_op("fusion.9", 0, 1), scope_map) is None


def test_scope_map_from_hlo_text():
    txt = '''
  %dot.0 = f32[8,8] dot(...), metadata={op_name="jit(f)/pcg/matvec/dot_general" source_file="x"}
  %add.2 = f32[8,8] add(...), metadata={op_name="jit(f)/pcg/axpy/add"}
  %mul.3 = f32[8,8] multiply(...), metadata={op_name="jit(f)/other/mul"}
'''
    m = profview.scope_map_from_hlo_text(txt)
    assert m == {"dot.0": "matvec", "add.2": "axpy"}


# ----------------------------------------------------------------------
# tolerant reader: gz + plain, truncated, missing lanes, missing file
# ----------------------------------------------------------------------

def _write_trace(path, events, gz=True):
    payload = json.dumps({"traceEvents": events}).encode()
    if gz:
        with gzip.open(path, "wb") as f:
            f.write(payload)
    else:
        with open(path, "wb") as f:
            f.write(payload)
    return path


def test_reader_gz_and_plain(tmp_path):
    evs = [{"ph": "X", "name": "dot.1", "ts": 0, "dur": 5,
            "pid": 1, "tid": 1, "args": {"hlo_op": "dot.1"}}]
    for fn, gz in (("a.trace.json.gz", True), ("b.trace.json", False)):
        p = _write_trace(str(tmp_path / fn), evs, gz=gz)
        got, probs = profview.read_trace_events(p)
        assert probs == [] and len(got) == 1
        assert len(profview.device_ops(got)) == 1


def test_reader_truncated_file_degrades_not_crashes(tmp_path):
    p = str(tmp_path / "t.trace.json.gz")
    _write_trace(p, [{"ph": "X", "name": "dot.1"}])
    with open(p, "rb") as f:
        blob = f.read()
    with open(p, "wb") as f:
        f.write(blob[: len(blob) // 2])     # the dead-tunnel artifact
    evs, probs = profview.read_trace_events(p)
    assert evs == [] and probs, probs
    rep = profview.profile_report(p)
    assert rep["verdict"].startswith("degraded:")
    assert rep["phases"]["matvec"]["ms"] == 0.0
    # the report still validates as a prof_report event
    rec = _CapturingRecorder()
    profview.emit_prof_report(rec, rep)
    assert validate_event(rec.events[0]) == []


def test_reader_missing_device_lanes_named_verdict(tmp_path):
    # host-only events (no hlo args): parse succeeds, verdict names it
    p = _write_trace(str(tmp_path / "h.trace.json.gz"),
                     [{"ph": "X", "name": "$builtins len", "ts": 0,
                       "dur": 5, "pid": 1, "tid": 1}])
    rep = profview.profile_report(p)
    assert "device lanes" in rep["verdict"] or "device-op" in rep["verdict"]
    assert rep["verdict"].startswith("degraded:")


def test_reader_missing_artifact_named_verdict(tmp_path):
    rep = profview.profile_report(str(tmp_path / "nowhere"))
    assert rep["verdict"].startswith("degraded:")
    assert "no trace artifact" in rep["verdict"]


def test_container_ops_excluded_from_device_ops():
    evs = [{"ph": "X", "name": "call.3", "ts": 0, "dur": 50, "pid": 1,
            "tid": 1, "args": {"hlo_op": "call.3"}},
           {"ph": "X", "name": "while.1", "ts": 0, "dur": 50, "pid": 1,
            "tid": 1, "args": {"hlo_op": "while.1"}},
           {"ph": "X", "name": "dot.1", "ts": 0, "dur": 5, "pid": 1,
            "tid": 1, "args": {"hlo_op": "dot.1"}}]
    ops = profview.device_ops(evs)
    assert [o["name"] for o in ops] == ["dot.1"]


# ----------------------------------------------------------------------
# CPU end-to-end: capture -> parse -> attribute (classic + pipelined)
# ----------------------------------------------------------------------

def test_capture_parse_attribute_roundtrip_classic(tmp_path):
    s = _solver(nx=6, n_parts=1)
    rec = _CapturingRecorder()
    cap = profview.capture_solve_profile(s, str(tmp_path / "prof"),
                                         recorder=rec)
    # the sidecar makes the artifact self-describing offline
    assert cap["meta_path"] and os.path.exists(cap["meta_path"])
    meta = json.load(open(cap["meta_path"]))
    assert meta["schema"] == profview.PROFVIEW_META_SCHEMA
    assert meta["pcg_variant"] == "classic" and meta["iters"] >= 1
    assert len(meta["scope_map"]) > 0
    # the profile_capture event fired with the artifact path
    caps = [e for e in rec.events if e["kind"] == "profile_capture"]
    assert len(caps) == 1 and caps[0]["path"] == cap["artifact"]
    assert validate_event(caps[0]) == []

    rep = profview.profile_report(cap["artifact"])
    assert rep["verdict"] == "ok"
    # every phase attributed with real events and time
    for ph in ("matvec", "precond", "reduction", "axpy"):
        assert rep["phases"][ph]["events"] > 0, (ph, rep["phases"])
        assert rep["phases"][ph]["ms_per_iter"] > 0
    # acceptance: the per-phase attribution sums to within 20% of the
    # anchor iteration time the trace can attribute (the device-op
    # total; the wall anchor additionally carries the CPU runtime's
    # inter-thunk scheduling gap, reported separately as runtime gap)
    assert rep["device_attribution"] >= 0.8, rep
    assert rep["sum_ms_per_iter"] == pytest.approx(
        rep["device_ms_per_iter"], rel=0.2)
    # classic negative control: the measured overlap is ~0 (1 device:
    # the trivial collectives never hide behind concurrent compute)
    assert rep["overlap_frac"] in (None, 0.0) or rep["overlap_frac"] < 0.05
    # the prof_report event validates against obs/schema.py
    profview.emit_prof_report(rec, rep)
    ev = [e for e in rec.events if e["kind"] == "prof_report"][0]
    assert validate_event(ev) == []
    assert rec.gauges["prof.matvec_ms_per_iter"] > 0


def test_capture_parse_pipelined_overlap_computed(tmp_path):
    """The hardware twin of PR 10's static psum-overlap rule, chipless:
    the traced pipelined program's report COMPUTES an overlap fraction
    (collectives present, intersection measured).  On CPU the number
    itself may be small — forced-host virtual devices share one pid,
    and a 1-core box serializes everything — the contract here is the
    parse/bucket/reconcile pipeline; the fraction is the hardware
    window's to confirm (tools/hw_session.py logs it)."""
    s = _solver(nx=6, n_parts=2, variant="pipelined")
    cap = profview.capture_solve_profile(s, str(tmp_path / "prof"))
    rep = profview.profile_report(cap["artifact"])
    assert rep["verdict"] == "ok"
    assert rep["overlap"]["n_collectives"] > 0
    assert rep["overlap_frac"] is not None
    assert 0.0 <= rep["overlap_frac"] <= 1.0
    for ph in ("matvec", "precond", "reduction", "axpy"):
        assert rep["phases"][ph]["events"] > 0


def test_prof_report_cli_offline(tmp_path, capsys):
    from pcg_mpi_solver_tpu import cli

    s = _solver(nx=6, n_parts=1)
    cap = profview.capture_solve_profile(s, str(tmp_path / "prof"))
    out_jsonl = str(tmp_path / "prof.jsonl")
    cli.main(["prof-report", cap["artifact"],
              "--telemetry-out", out_jsonl])
    out = capsys.readouterr().out
    assert "matvec" in out and "verdict: ok" in out
    assert "predicted" in out        # rebuilt from the sidecar meta
    evs = [json.loads(l) for l in open(out_jsonl)]
    assert evs and evs[0]["kind"] == "prof_report"
    assert validate_event(evs[0]) == []
    # a missing artifact exits 2, after printing the degraded verdict
    with pytest.raises(SystemExit) as e:
        cli.main(["prof-report", str(tmp_path / "missing")])
    assert e.value.code == 2


def test_perf_report_cli_measured_column(tmp_path, capsys):
    """The extended perf-report: --profile-dir adds the trace-measured
    column next to predicted (cost model) and recorded (probes)."""
    from pcg_mpi_solver_tpu import cli

    cli.main(["perf-report", "--nx", "6", "--reps", "1", "--inner", "2",
              "--max-iter", "200",
              "--profile-dir", str(tmp_path / "prof")])
    out = capsys.readouterr().out
    assert "predicted" in out and "recorded" in out and "measured" in out
    assert "collective overlap" in out
    assert "verdict:" in out


# ----------------------------------------------------------------------
# capture respects the scope-map contract end to end
# ----------------------------------------------------------------------

def test_scope_map_from_solver_nonempty_all_variants():
    for variant in ("classic", "fused", "pipelined"):
        s = _solver(nx=4, n_parts=1, variant=variant)
        m = profview.scope_map_from_solver(s)
        phases = set(m.values())
        assert {"matvec", "reduction", "axpy"} <= phases, (variant, phases)


# ----------------------------------------------------------------------
# trend sentinel over the committed artifacts
# ----------------------------------------------------------------------

def test_trend_parses_committed_artifacts():
    arts = sorted(glob.glob(os.path.join(REPO, "BENCH_r0*.json")))
    assert len(arts) >= 5
    rep = trend.trend_report(arts)
    # r01..r03 are failed-round wrappers (rc != 0, parsed null): they
    # contribute zero lines but must parse without error
    by_label = {s["label"]: s["lines"] for s in rep["sources"]}
    assert by_label["BENCH_r01.json"] == 0
    assert by_label["BENCH_r04.json"] >= 1
    assert by_label["BENCH_r05.json"] >= 1
    # r04 (46875 dofs) and r05 (10.3M dofs) are different legs: matched
    # pairs cannot be fabricated across shapes
    assert rep["regressed"] == 0
    assert rep["single"] >= 2
    legs = {l["leg"] for l in rep["legs"]}
    assert any("10328853" in l for l in legs)


def test_trend_seeded_regression_exits_nonzero(tmp_path, capsys):
    arts = sorted(glob.glob(os.path.join(REPO, "BENCH_r0*.json")))
    fresh_line = json.load(open(
        os.path.join(REPO, "BENCH_r05.json")))["parsed"]
    fresh = dict(fresh_line, value=fresh_line["value"] * 0.5)
    fp = str(tmp_path / "fresh.json")
    json.dump(fresh, open(fp, "w"))
    rep = trend.trend_report(arts, fresh=fp)
    assert rep["regressed"] == 1
    reg = [l for l in rep["legs"] if l["verdict"] == "regressed"][0]
    assert reg["delta_pct"] == pytest.approx(-50.0)
    assert "REGRESSED" in trend.verdict_line(rep)
    # the CLI exit code reflects the regression
    from pcg_mpi_solver_tpu import cli

    with pytest.raises(SystemExit) as e:
        cli.main(["trend"] + arts + ["--fresh", fp])
    assert e.value.code == 1
    assert "REGRESSION" in capsys.readouterr().out


def test_trend_improved_and_flat_verdicts(tmp_path):
    base = {"metric": "m", "value": 100.0, "unit": "u",
            "vs_baseline": 1.0,
            "detail": {"model": "cube", "n_dof": 1000, "mode": "mixed",
                       "backend": "general"}}
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    json.dump(base, open(a, "w"))
    json.dump(dict(base, value=130.0), open(b, "w"))
    rep = trend.trend_report([a, b])
    assert rep["improved"] == 1 and rep["regressed"] == 0
    json.dump(dict(base, value=103.0), open(b, "w"))
    rep = trend.trend_report([a, b])
    assert rep["flat"] == 1


def test_trend_matches_by_variant_precond_nrhs(tmp_path):
    """A fused leg must never compare against a classic leg of the same
    shape — the key includes variant/precond/nrhs; pre-schema lines
    (no fields) match under the historical defaults."""
    d = {"model": "cube", "n_dof": 1000, "mode": "mixed",
         "backend": "general"}
    base = {"metric": "m", "value": 100.0, "unit": "u",
            "vs_baseline": 1.0, "detail": d}
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    json.dump(base, open(a, "w"))
    json.dump(dict(base, value=50.0,
                   detail=dict(d, pcg_variant="fused")), open(b, "w"))
    rep = trend.trend_report([a, b])
    assert rep["regressed"] == 0 and rep["single"] == 2
    # explicit classic/jacobi/nrhs=1 matches a pre-schema line
    json.dump(dict(base, value=50.0,
                   detail=dict(d, pcg_variant="classic",
                               precond="jacobi", nrhs=1)), open(b, "w"))
    rep = trend.trend_report([a, b])
    assert rep["regressed"] == 1


def test_trend_zero_value_sentinel_skipped(tmp_path):
    err = {"metric": "m", "value": 0.0, "unit": "u", "vs_baseline": 0.0,
           "detail": {"error": "no solve completed"}}
    p = str(tmp_path / "err.json")
    json.dump(err, open(p, "w"))
    assert trend.iter_bench_lines(p) == []


def test_trend_round_wrapper_tail_lines_deduped(tmp_path):
    """The committed round wrappers repeat the parsed line inside the
    tail — one leg, not two."""
    line = {"metric": "m", "value": 5.0, "unit": "u", "vs_baseline": 1.0,
            "detail": {"model": "cube", "n_dof": 10, "mode": "mixed",
                       "backend": "general"}}
    wrapper = {"n": 9, "cmd": "x", "rc": 0,
               "tail": "noise\n" + json.dumps(line) + "\n",
               "parsed": line}
    p = str(tmp_path / "w.json")
    json.dump(wrapper, open(p, "w"))
    assert len(trend.iter_bench_lines(p)) == 1


# ----------------------------------------------------------------------
# bench wiring (BENCH_PROFILE) + schema stamps
# ----------------------------------------------------------------------

def test_bench_profile_capture_stamps_detail(tmp_path, monkeypatch):
    """_capture_bench_profile returns the schema-typed detail fields on
    a live capture, {} when BENCH_PROFILE is off, and {} (with a log
    breadcrumb, never a raise) when the capture explodes."""
    from pcg_mpi_solver_tpu import bench
    from pcg_mpi_solver_tpu.obs.schema import BENCH_DETAIL_NUMERIC

    assert "measured_ms_per_iter_matvec" in BENCH_DETAIL_NUMERIC
    assert "overlap_frac" in BENCH_DETAIL_NUMERIC

    s = _solver(nx=5, n_parts=1, max_iter=150)
    monkeypatch.delenv("BENCH_PROFILE", raising=False)
    assert bench._capture_bench_profile(s, 1) == {}

    monkeypatch.setenv("BENCH_PROFILE", "1")
    monkeypatch.setenv("BENCH_PROFILE_DIR", str(tmp_path / "bp"))
    out = bench._capture_bench_profile(s, 1)
    assert out["measured_ms_per_iter_matvec"] > 0
    # single device: no collectives -> overlap_frac absent or a number
    if "overlap_frac" in out:
        assert 0.0 <= out["overlap_frac"] <= 1.0
    # the artifact is on disk for pcg-tpu prof-report
    assert profview.find_trace_files(str(tmp_path / "bp"))

    # a broken capture must not cost the bench its number
    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(profview, "capture_solve_profile", boom)
    assert bench._capture_bench_profile(s, 1) == {}


def test_profile_capture_event_on_solve_path(tmp_path):
    """The driver's profile_dir bracket (the historical dynamics-path
    capture) now emits profile_capture with the artifact path, and the
    offline summary points at it."""
    from pcg_mpi_solver_tpu.config import TimeHistoryConfig
    from pcg_mpi_solver_tpu.obs.metrics import summarize_jsonl

    model = make_cube_model(3, 3, 3)
    cfg = RunConfig(
        scratch_path=str(tmp_path),
        profile_dir=str(tmp_path / "trace"),
        solver=SolverConfig(tol=1e-6, max_iter=100),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                       export_flag=False),
    )
    cfg.telemetry_path = str(tmp_path / "run.jsonl")
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    s.solve()
    evs = [json.loads(l) for l in open(cfg.telemetry_path)]
    caps = [e for e in evs if e["kind"] == "profile_capture"]
    assert len(caps) == 1
    assert caps[0]["source"] == "solve"
    assert os.path.isdir(caps[0]["path"])
    assert validate_event(caps[0]) == []
    # the captured artifact parses (no sidecar on this path -> the
    # reader degrades by NAME, it does not crash)
    rep = profview.profile_report(caps[0]["path"])
    assert rep["n_device_ops"] > 0
    assert "summary" not in rep        # sanity: it's a report dict
    txt = summarize_jsonl(cfg.telemetry_path)
    assert "profile artifact:" in txt and "prof-report" in txt


# ----------------------------------------------------------------------
# review-hardening regressions (ISSUE 15 review pass)
# ----------------------------------------------------------------------

def test_trend_same_round_duplicates_cannot_shadow_regression(tmp_path):
    """A round whose artifact carries the final line NEXT TO an
    insurance near-duplicate (same leg, different value — dedup misses
    it) must still compare CROSS-round: the duplicate pair inside one
    round must not shadow a real cross-round regression."""
    d = {"model": "cube", "n_dof": 1000, "mode": "mixed",
         "backend": "general"}
    old = {"metric": "m", "value": 150.0, "unit": "u",
           "vs_baseline": 1.0, "detail": d}
    final = dict(old, value=98.0)
    insurance = dict(old, value=100.0)
    a, b = str(tmp_path / "r1.json"), str(tmp_path / "r2.json")
    json.dump(old, open(a, "w"))
    json.dump({"n": 2, "cmd": "x", "rc": 0,
               "tail": json.dumps(insurance) + "\n", "parsed": final},
              open(b, "w"))
    rep = trend.trend_report([a, b])
    reg = [l for l in rep["legs"] if l["verdict"] == "regressed"]
    assert len(reg) == 1, rep["legs"]
    # compared against the round's BEST value (100), across rounds
    assert reg[0]["old_value"] == 150.0
    assert reg[0]["new_value"] == 100.0


def test_trend_failed_round_tail_contributes_nothing(tmp_path):
    """rc != 0 wrapper: provisional/insurance lines stranded in its
    tail are not that round's measurement — they must not become the
    leg's newest value."""
    d = {"model": "cube", "n_dof": 1000, "mode": "mixed",
         "backend": "general"}
    line = {"metric": "m", "value": 50.0, "unit": "u",
            "vs_baseline": 1.0, "detail": d}
    p = str(tmp_path / "dead.json")
    json.dump({"n": 3, "cmd": "x", "rc": 124,
               "tail": json.dumps(line) + "\n", "parsed": None},
              open(p, "w"))
    assert trend.iter_bench_lines(p) == []


def test_trend_exit_2_when_no_bench_lines(tmp_path, capsys):
    p = str(tmp_path / "empty.json")
    json.dump({"n": 1, "cmd": "x", "rc": 1, "tail": "", "parsed": None},
              open(p, "w"))
    assert trend.main_cli([p]) == 2
    assert "nothing to compare" in capsys.readouterr().out
    rep = trend.trend_report([p])
    assert "no matched legs" in trend.verdict_line(rep)


def test_format_report_zero_duration_collectives_no_crash(tmp_path):
    """Collective ops with zero total duration (bare async markers):
    overlap_frac is None while n_collectives > 0 — format_report must
    render n/a, not crash on None formatting."""
    p = _write_trace(str(tmp_path / "z.trace.json.gz"),
                     [{"ph": "X", "name": "all-reduce.0", "ts": 0,
                       "dur": 0, "pid": 1, "tid": 1,
                       "args": {"hlo_op": "all-reduce.0"}},
                      {"ph": "X", "name": "dot.1", "ts": 0, "dur": 5,
                       "pid": 1, "tid": 2,
                       "args": {"hlo_op": "dot.1"}}])
    rep = profview.profile_report(p)
    assert rep["overlap_frac"] is None
    assert rep["overlap"]["n_collectives"] == 1
    txt = profview.format_report(rep)
    assert "zero duration" in txt


def test_sidecar_unknown_scope_label_counted():
    """The scope-labels loudness contract on the CPU sidecar path: a
    pcg/<x> label outside the known phases arriving via the compiled
    HLO scope map is counted into unknown_scopes, not silently folded
    into 'other' anonymously."""
    smap = profview.scope_map_from_hlo_text(
        '%ghost.1 = f32[2]{0} add(...), '
        'metadata={op_name="jit(f)/pcg/halo/add"}\n'
        '%dot.1 = f32[2]{0} dot(...), '
        'metadata={op_name="jit(f)/pcg/matvec/dot_general"}')
    assert smap == {"ghost.1": "?halo", "dot.1": "matvec"}
    b = profview.bucket_phases(
        [_op("ghost.1", 0, 5), _op("ghost.2", 10, 5), _op("dot.1", 0, 7)],
        smap)
    assert b["unknown_scopes"] == {"halo": 2}     # exact + base-name hit
    assert b["phases"]["matvec"]["us"] == 7.0
    assert b["other_events"] == 2                 # counted, not dropped
