"""Checkpoint/resume: a multi-step solve interrupted after step k and
resumed must reproduce the uninterrupted run exactly (histories, solution,
export frames).  The reference has no in-solve checkpointing (SURVEY.md §5)
— this is a capability the TPU framework adds."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver import Solver
from pcg_mpi_solver_tpu.utils.checkpoint import CheckpointManager
from pcg_mpi_solver_tpu.utils.io import RunStore


def _cfg(tmp_path, run_id="1", every=1, plot=False):
    return RunConfig(
        scratch_path=str(tmp_path),
        run_id=run_id,
        checkpoint_every=every,
        solver=SolverConfig(tol=1e-8, max_iter=500),
        time_history=TimeHistoryConfig(
            time_step_delta=[0.0, 0.25, 0.5, 1.0],
            export_frame_rate=1,
            plot_flag=plot,
            probe_dofs=(3, 10) if plot else (),
        ),
    )


@pytest.fixture(scope="module")
def model():
    return make_cube_model(5, 4, 4, heterogeneous=True)


def test_resume_matches_uninterrupted(tmp_path, model):
    # Full uninterrupted run.
    cfg_a = _cfg(tmp_path, run_id="a", every=0)
    sa = Solver(model, cfg_a, mesh=make_mesh(4), n_parts=4)
    store_a = RunStore(cfg_a.result_path)
    sa.solve(store=store_a)

    # Interrupted run: stop after step 2 (simulated by a truncated schedule
    # sharing the same checkpoint dir), then resume with the full schedule.
    cfg_b = _cfg(tmp_path, run_id="b", every=1)
    sb1 = Solver(model, cfg_b, mesh=make_mesh(4), n_parts=4)
    store_b = RunStore(cfg_b.result_path)
    steps_run = []

    def interrupt_after_2(t, r):
        steps_run.append(t)
        if t == 2:
            raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        sb1.solve(store=store_b, on_step=interrupt_after_2)
    assert max(steps_run) == 2

    sb2 = Solver(model, cfg_b, mesh=make_mesh(4), n_parts=4)
    resumed = []
    sb2.solve(store=store_b, resume=True, on_step=lambda t, r: resumed.append(t))
    assert resumed == [3]

    # Histories identical to the uninterrupted run.
    assert sb2.iters == sa.iters
    assert sb2.flags == sa.flags
    np.testing.assert_allclose(sb2.relres, sa.relres, rtol=1e-12)
    np.testing.assert_allclose(sb2.displacement_global(),
                               sa.displacement_global(), rtol=1e-12, atol=0)

    # Export frames identical (frame 0 + 3 steps).
    assert store_b.n_frames("U") == store_a.n_frames("U") == 4
    for k in range(4):
        np.testing.assert_allclose(store_b.read_frame("U", k),
                                   store_a.read_frame("U", k),
                                   rtol=1e-12, atol=0)


def test_fingerprint_mismatch_raises(tmp_path, model):
    cfg = _cfg(tmp_path, run_id="c", every=1)
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    s.solve()

    cfg2 = _cfg(tmp_path, run_id="c", every=1)
    cfg2.solver = SolverConfig(tol=1e-4, max_iter=500)   # different tol
    s2 = Solver(model, cfg2, mesh=make_mesh(4), n_parts=4)
    mgr = CheckpointManager(cfg2.checkpoint_path)
    with pytest.raises(ValueError, match="mismatch"):
        mgr.restore(s2)


def test_model_content_mismatch_raises(tmp_path, model):
    """Resuming against a model of identical shapes but different content
    (here: a perturbed stiffness field) must be rejected — shape-only
    fingerprints would silently produce garbage (VERDICT round 1)."""
    import dataclasses

    cfg = _cfg(tmp_path, run_id="cm", every=1)
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    s.solve()

    mutated = dataclasses.replace(model, ck=model.ck * 1.5)
    cfg2 = _cfg(tmp_path, run_id="cm", every=1)
    s2 = Solver(mutated, cfg2, mesh=make_mesh(4), n_parts=4)
    mgr = CheckpointManager(cfg2.checkpoint_path)
    with pytest.raises(ValueError, match="mismatch"):
        mgr.restore(s2)


def test_material_law_mismatch_raises(tmp_path, model):
    """A different Poisson ratio changes only the element library (ck/F/Ud
    etc. are byte-identical), so the fingerprint must hash Ke/mat_prop too."""
    cfg = _cfg(tmp_path, run_id="nu", every=1)
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    s.solve()

    mutated = make_cube_model(5, 4, 4, nu=0.25, heterogeneous=True)
    cfg2 = _cfg(tmp_path, run_id="nu", every=1)
    s2 = Solver(mutated, cfg2, mesh=make_mesh(4), n_parts=4)
    mgr = CheckpointManager(cfg2.checkpoint_path)
    with pytest.raises(ValueError, match="mismatch"):
        mgr.restore(s2)


def test_resume_without_checkpoint_is_fresh(tmp_path, model):
    cfg = _cfg(tmp_path, run_id="d", every=0)
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    res = s.solve(resume=True)       # no checkpoint dir -> full run
    assert len(res) == 3


def test_checkpoint_files_and_latest(tmp_path, model):
    cfg = _cfg(tmp_path, run_id="e", every=2)
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    s.solve()
    mgr = CheckpointManager(cfg.checkpoint_path)
    # steps 2 (every=2) and 3 (final) are checkpointed
    assert mgr.latest_step() == 3
    assert mgr.restore(Solver(model, cfg, mesh=make_mesh(4), n_parts=4)) == 3


def test_probe_history_survives_resume(tmp_path, model):
    cfg_a = _cfg(tmp_path, run_id="f", every=0, plot=True)
    sa = Solver(model, cfg_a, mesh=make_mesh(4), n_parts=4)
    store_a = RunStore(cfg_a.result_path)
    sa.solve(store=store_a)

    cfg_b = _cfg(tmp_path, run_id="g", every=1, plot=True)
    sb = Solver(model, cfg_b, mesh=make_mesh(4), n_parts=4)
    store_b = RunStore(cfg_b.result_path)
    try:
        sb.solve(store=store_b,
                 on_step=lambda t, r: (_ for _ in ()).throw(KeyboardInterrupt)
                 if t == 2 else None)
    except KeyboardInterrupt:
        pass
    sb2 = Solver(model, cfg_b, mesh=make_mesh(4), n_parts=4)
    sb2.solve(store=store_b, resume=True)

    def plot_u(path):
        z = np.load(f"{path}/model_PlotData.npz", allow_pickle=True)
        return z["PlotData"].item()["Plot_U"]

    np.testing.assert_allclose(plot_u(cfg_b.plot_path),
                               plot_u(cfg_a.plot_path), rtol=1e-12)


def test_latest_pointer_fallback_to_newest_valid(tmp_path, model):
    """When the `latest` pointer references a missing or corrupt
    ckpt_*.npz, resume falls back to the newest VALID checkpoint instead
    of silently starting fresh (ISSUE 3 satellite)."""
    import os

    cfg = _cfg(tmp_path, run_id="fb", every=1)
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    s.solve()
    mgr = CheckpointManager(cfg.checkpoint_path)
    assert mgr.latest_step() == 3

    # corrupt the pointer's target (truncated write): fall back to t=2
    latest = os.path.join(cfg.checkpoint_path, "ckpt_000003.npz")
    blob = open(latest, "rb").read()
    with open(latest, "wb") as f:
        f.write(blob[: len(blob) // 3])
    with pytest.warns(UserWarning, match="falling back"):
        assert mgr.latest_step() == 2
    s2 = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    with pytest.warns(UserWarning, match="falling back"):
        assert mgr.restore(s2) == 2

    # remove it entirely (dangling pointer): same fallback
    os.remove(latest)
    with pytest.warns(UserWarning, match="falling back"):
        assert mgr.latest_step() == 2

    # no valid checkpoint at all -> None (fresh run), not a crash
    for f in os.listdir(cfg.checkpoint_path):
        if f.startswith("ckpt_"):
            os.remove(os.path.join(cfg.checkpoint_path, f))
    assert mgr.latest_step() is None


def test_kill_and_resume_mid_solve_parity(tmp_path, model):
    """ISSUE 3 acceptance (a): a chunked solve killed at a chunk
    boundary (injected SimulatedKill) and resumed produces the same
    final flag/relres and BIT-IDENTICAL convergence history as an
    uninterrupted solve — the mid-Krylov snapshot loses at most one
    chunk and the resumed Krylov recurrence replays exactly."""
    from pcg_mpi_solver_tpu.resilience import FaultPlan, SimulatedKill

    def _cfg_chunked(run_id):
        cfg = _cfg(tmp_path, run_id=run_id, every=1)
        cfg.solver.iters_per_dispatch = 12   # force the chunked path
        cfg.solver.trace_resid = 64          # ring rides the snapshots
        cfg.snapshot_every = 1
        return cfg

    cfg_a = _cfg_chunked("ka")
    sa = Solver(model, cfg_a, mesh=make_mesh(4), n_parts=4)
    sa.solve()
    trace_a = sa.last_trace

    cfg_b = _cfg_chunked("kb")
    sb = Solver(model, cfg_b, mesh=make_mesh(4), n_parts=4)
    sb.fault_plan = FaultPlan("kill@3")      # die mid-step at boundary 3
    with pytest.raises(SimulatedKill):
        sb.solve()
    import os

    snaps = [f for f in os.listdir(cfg_b.checkpoint_path)
             if f.startswith("snap_")]
    assert snaps, "the kill must leave a mid-Krylov snapshot behind"

    sb2 = Solver(model, cfg_b, mesh=make_mesh(4), n_parts=4)
    sb2.solve(resume=True)

    # bit-identical history: same flags, EXACT relres/iters equality,
    # exact solution bytes, and the in-graph convergence ring (which
    # rode the snapshot across the kill) matches sample for sample
    assert sb2.flags == sa.flags
    assert sb2.iters == sa.iters
    assert sb2.relres == sa.relres
    np.testing.assert_array_equal(sb2.displacement_global(),
                                  sa.displacement_global())
    trace_b = sb2.last_trace
    assert trace_b.n_recorded == trace_a.n_recorded
    np.testing.assert_array_equal(trace_b.normr, trace_a.normr)
    # completed steps discarded their snapshots
    assert not [f for f in os.listdir(cfg_b.checkpoint_path)
                if f.startswith("snap_")]


def test_resume_rejects_flipped_stencil_knobs(tmp_path):
    """The matvec form and hybrid block layout change the stencil's
    summation order (same exact-resume hazard as the Pallas variants):
    a resume under a flipped knob must be refused, not silently drift."""
    import os

    from pcg_mpi_solver_tpu.models.octree import make_octree_model

    model = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3)
    cfg = RunConfig(scratch_path=str(tmp_path), checkpoint_every=1,
                    solver=SolverConfig(tol=1e-8, max_iter=50),
                    time_history=TimeHistoryConfig(
                        time_step_delta=[0.0, 1.0]))
    mgr = CheckpointManager(str(tmp_path / "ck"))

    def build():
        return Solver(model, cfg, mesh=make_mesh(1), n_parts=1,
                      backend="hybrid")

    prev = {k: os.environ.get(k)
            for k in ("PCG_TPU_MATVEC_FORM", "PCG_TPU_HYBRID_BLOCK")}
    try:
        os.environ.pop("PCG_TPU_MATVEC_FORM", None)
        os.environ["PCG_TPU_HYBRID_BLOCK"] = "2"
        s = build()
        s.step(1.0)
        mgr.save(s, 1)

        # same env: restores fine
        assert mgr.restore(build(), 1) == 1

        # flipped form: refused
        os.environ["PCG_TPU_MATVEC_FORM"] = "corner"
        with pytest.raises(ValueError, match="matvec_form"):
            mgr.restore(build(), 1)
        os.environ.pop("PCG_TPU_MATVEC_FORM", None)

        # flipped block layout: refused
        os.environ["PCG_TPU_HYBRID_BLOCK"] = "1000000"
        with pytest.raises(ValueError, match="level_dims"):
            mgr.restore(build(), 1)
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
