"""Static collective-count contract of the PCG loop body
(tools/check_collectives.py): the fused Chronopoulos–Gear variant must
run exactly ONE scalar-reduction psum per iteration, and classic must
keep its documented three — a regression here silently re-serializes
the hot loop and only shows up as ms/iteration in a scarce hardware
window."""

from tools.check_collectives import (
    EXPECTED_BODY_PSUMS, iteration_psum_count, run_checks)


def test_documented_psum_counts_hold():
    """classic = 5 body psums (iface + rho/inf + pq + 3-norm + deferred
    check), fused = 3 (iface + THE fused reduction + deferred check)."""
    assert run_checks() == []


def test_fused_saves_exactly_two_psums():
    classic = iteration_psum_count("classic")
    fused = iteration_psum_count("fused")
    assert classic == EXPECTED_BODY_PSUMS["classic"]
    assert fused == EXPECTED_BODY_PSUMS["fused"]
    assert fused == classic - 2


def test_batched_body_psum_count_independent_of_nrhs():
    """The ISSUE-6 headline claim, lint-enforced: the blocked multi-RHS
    body (pcg_many) runs EXACTLY the single-RHS psum count — widening
    the block widens payloads, never the collective count."""
    for variant, want in EXPECTED_BODY_PSUMS.items():
        assert iteration_psum_count(variant, nrhs=8) == want
        assert iteration_psum_count(variant, nrhs=2) == want


def test_comm_estimate_gauges_match_the_claim():
    """Ops.comm_estimate (the telemetry gauge source) must advertise the
    same per-iteration psum counts the traced bodies prove: classic
    3 scalar psums + iface, fused 1 + iface."""
    import dataclasses

    from pcg_mpi_solver_tpu.ops.matvec import Ops

    ops = Ops(n_loc=8, n_iface=4)
    assert ops.comm_estimate()["psums_per_iter"] == 4
    assert ops.comm_estimate(variant="fused")["psums_per_iter"] == 2
    assert ops.comm_estimate(variant="fused")["pcg_variant"] == "fused"
    # no interface (single part): the matvec psum disappears either way
    ops1 = dataclasses.replace(ops, n_iface=0)
    assert ops1.comm_estimate()["psums_per_iter"] == 3
    assert ops1.comm_estimate(variant="fused")["psums_per_iter"] == 1
