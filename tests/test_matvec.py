"""Matrix-free K.x vs dense assembly — the core correctness property
(reference has no such test; SURVEY.md §4 gap)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
from pcg_mpi_solver_tpu.parallel.mesh import PARTS_AXIS, make_mesh
from pcg_mpi_solver_tpu.parallel.partition import partition_model


def global_to_parts(pm, x_glob):
    """Scatter a global vector into (P, n_loc) padded part-local views."""
    out = np.zeros((pm.n_parts, pm.n_loc))
    for p in range(pm.n_parts):
        n = pm.ndof_p[p]
        out[p, :n] = x_glob[pm.dof_gid[p, :n]]
    return out


def parts_to_global(pm, y_parts):
    """Owner-masked reassembly of a part-padded vector to global."""
    out = np.zeros(pm.glob_n_dof)
    m = (pm.weight > 0) & (pm.dof_gid >= 0)
    out[pm.dof_gid[m]] = np.asarray(y_parts)[m]
    return out


@pytest.mark.parametrize("n_parts,n_types,hetero", [(1, 1, False), (4, 3, True)])
def test_matvec_vs_dense_unsharded(n_parts, n_types, hetero):
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, n_types=n_types,
                            heterogeneous=hetero)
    pm = partition_model(model, n_parts)
    data = device_data(pm)
    ops = Ops.from_model(pm)  # axis_name=None: unsharded reference path

    rng = np.random.default_rng(1)
    x = rng.normal(size=model.n_dof)
    y_ref = model.assemble_csr() @ x

    y = ops.matvec(data, jnp.asarray(global_to_parts(pm, x)))
    np.testing.assert_allclose(parts_to_global(pm, y), y_ref, rtol=1e-10, atol=1e-10)


def test_matvec_consistency_on_duplicated_dofs():
    """After interface assembly every copy of a shared dof holds the same
    (fully assembled) value — the invariant the halo exchange maintains."""
    model = make_cube_model(4, 4, 4)
    pm = partition_model(model, 4)
    data = device_data(pm)
    ops = Ops.from_model(pm)

    x = np.random.default_rng(2).normal(size=model.n_dof)
    y = np.asarray(ops.matvec(data, jnp.asarray(global_to_parts(pm, x))))

    y_ref = model.assemble_csr() @ x
    for p in range(pm.n_parts):
        n = pm.ndof_p[p]
        np.testing.assert_allclose(y[p, :n], y_ref[pm.dof_gid[p, :n]],
                                   rtol=1e-10, atol=1e-10)
        # padding stays zero
        assert np.all(y[p, n:] == 0)


def test_matvec_sharded_8dev():
    """Same numbers under real SPMD over the 8 virtual CPU devices."""
    model = make_cube_model(6, 4, 4, heterogeneous=True)
    pm = partition_model(model, 8)
    data = device_data(pm)
    ops = Ops.from_model(pm, axis_name=PARTS_AXIS)
    mesh = make_mesh(8)

    P = jax.sharding.PartitionSpec

    def f(data, x):
        return ops.matvec(data, x)

    from pcg_mpi_solver_tpu.solver.driver import _data_specs
    specs = _data_specs(data)
    shmap = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(specs, P(PARTS_AXIS)),
        out_specs=P(PARTS_AXIS), check_vma=False))

    x = np.random.default_rng(3).normal(size=model.n_dof)
    y = shmap(data, jnp.asarray(global_to_parts(pm, x)))
    y_ref = model.assemble_csr() @ x
    np.testing.assert_allclose(parts_to_global(pm, y), y_ref, rtol=1e-9, atol=1e-9)


def test_diag_vs_assembled():
    model = make_cube_model(3, 3, 3, n_types=2)
    pm = partition_model(model, 4)
    data = device_data(pm)
    ops = Ops.from_model(pm)
    d = np.asarray(ops.diag(data))
    np.testing.assert_allclose(parts_to_global(pm, d), model.assemble_diag(),
                               rtol=1e-12)


def test_sign_vector_reflection():
    """Mirrored-pattern sign trick: S.Ke.(S.u) == assembled K with
    S-conjugated element matrices (reference pcg_solver.py:277-280)."""
    model = make_cube_model(3, 2, 2)
    # flip a deterministic subset of element-dof signs
    rng = np.random.default_rng(7)
    model.elem_sign_flat = rng.random(model.elem_sign_flat.shape) < 0.3
    pm = partition_model(model, 2)
    data = device_data(pm)
    ops = Ops.from_model(pm)

    x = rng.normal(size=model.n_dof)
    y = parts_to_global(pm, ops.matvec(data, jnp.asarray(global_to_parts(pm, x))))
    y_ref = model.assemble_csr() @ x  # assemble_csr applies the same signs
    np.testing.assert_allclose(y, y_ref, rtol=1e-10, atol=1e-10)


def test_weights_count_each_dof_once():
    model = make_cube_model(4, 4, 4)
    pm = partition_model(model, 8)
    m = (pm.weight > 0) & (pm.dof_gid >= 0)
    gids = pm.dof_gid[m]
    assert len(gids) == model.n_dof
    assert len(np.unique(gids)) == model.n_dof
