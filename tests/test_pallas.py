"""Pallas fused structured matvec vs the XLA gather/einsum/scatter path.

Run in interpret mode (tests execute on the CPU backend, conftest.py); the
same kernel lowers to Mosaic on real TPU."""

import jax.numpy as jnp
import numpy as np
import pytest

from pcg_mpi_solver_tpu.models import make_cube_model
from pcg_mpi_solver_tpu.ops.pallas_matvec import structured_matvec_pallas
from pcg_mpi_solver_tpu.parallel.structured import (
    StructuredOps, device_data_structured, partition_structured)


@pytest.mark.parametrize("dims", [(6, 5, 4), (4, 4, 4), (7, 3, 5)])
def test_pallas_matvec_matches_xla(dims):
    nx, ny, nz = dims
    model = make_cube_model(nx, ny, nz, heterogeneous=True, seed=11)
    sp = partition_structured(model, 1)
    data = device_data_structured(sp, jnp.float32)
    ops = StructuredOps.from_partition(sp, dot_dtype=jnp.float32)

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(1, sp.n_loc)), jnp.float32)
    y_ref = np.asarray(ops.matvec_local(data, x))[0]

    blk = data["blocks"][0]
    xg = x.reshape(1, 3, nx + 1, ny + 1, nz + 1)[0]
    y = structured_matvec_pallas(xg, blk["ck"][0], blk["Ke"],
                                 interpret=True)
    np.testing.assert_allclose(
        np.asarray(y).reshape(-1), y_ref, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("dims", [(6, 5, 4), (4, 4, 4), (7, 3, 5)])
def test_pallas_matvec_v2_matches_xla(dims):
    from pcg_mpi_solver_tpu.ops.pallas_matvec import structured_matvec_pallas_v2

    nx, ny, nz = dims
    model = make_cube_model(nx, ny, nz, heterogeneous=True, seed=11)
    sp = partition_structured(model, 1)
    data = device_data_structured(sp, jnp.float32)
    ops = StructuredOps.from_partition(sp, dot_dtype=jnp.float32)

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(1, sp.n_loc)), jnp.float32)
    y_ref = np.asarray(ops.matvec_local(data, x))[0]

    blk = data["blocks"][0]
    xg = x.reshape(1, 3, nx + 1, ny + 1, nz + 1)[0]
    y = structured_matvec_pallas_v2(xg, blk["ck"][0], blk["Ke"],
                                    interpret=True)
    np.testing.assert_allclose(
        np.asarray(y).reshape(-1), y_ref, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("variant", ["v3", "v4", "v5", "v6", "v7", "v8", "v9"])
@pytest.mark.parametrize("dims,planes", [((6, 5, 4), 2), ((4, 4, 4), 4),
                                         ((7, 3, 5), 3), ((5, 4, 3), 8)])
def test_pallas_matvec_chunked_matches_xla(variant, dims, planes):
    """Chunked variants vs the XLA matvec, incl. chunk sizes that do not
    divide nx+1 (tail handled by zero padding / skipped copies):
    v3 double-buffered MXU, v4 reshape-free, v5 layout-legal (canonical
    per-corner dots, aligned pad + lane roll), v6 slab-aligned DMA."""
    from pcg_mpi_solver_tpu.ops import pallas_matvec as pm

    fn = {"v3": pm.structured_matvec_pallas_v3,
          "v4": pm.structured_matvec_pallas_v4,
          "v5": pm.structured_matvec_pallas_v5,
          "v6": pm.structured_matvec_pallas_v6,
          "v7": pm.structured_matvec_pallas_v7,
          "v8": pm.structured_matvec_pallas_v8,
          "v9": pm.structured_matvec_pallas_v9}[variant]
    nx, ny, nz = dims
    model = make_cube_model(nx, ny, nz, heterogeneous=True, seed=11)
    sp = partition_structured(model, 1)
    data = device_data_structured(sp, jnp.float32)
    ops = StructuredOps.from_partition(sp, dot_dtype=jnp.float32)

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(1, sp.n_loc)), jnp.float32)
    y_ref = np.asarray(ops.matvec_local(data, x))[0]

    blk = data["blocks"][0]
    xg = x.reshape(1, 3, nx + 1, ny + 1, nz + 1)[0]
    y = fn(xg, blk["ck"][0], blk["Ke"], interpret=True, planes=planes)
    np.testing.assert_allclose(
        np.asarray(y).reshape(-1), y_ref, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("kernel_fn", ["v1", "v2", "v3", "v4", "v5", "v6", "v7", "v8", "v9"])
def test_pallas_matvec_zero_ck_column_isolated(kernel_fn):
    """Cells with ck=0 must contribute nothing (the padded-cell trick the
    sharded integration — and v2's own gather padding — relies on)."""
    from pcg_mpi_solver_tpu.ops.pallas_matvec import (
        structured_matvec_pallas_v2, structured_matvec_pallas_v3,
        structured_matvec_pallas_v4, structured_matvec_pallas_v5,
        structured_matvec_pallas_v6, structured_matvec_pallas_v7,
        structured_matvec_pallas_v8, structured_matvec_pallas_v9)

    fn = {"v1": structured_matvec_pallas,
          "v2": structured_matvec_pallas_v2,
          "v3": structured_matvec_pallas_v3,
          "v4": structured_matvec_pallas_v4,
          "v5": structured_matvec_pallas_v5,
          "v6": structured_matvec_pallas_v6,
          "v7": structured_matvec_pallas_v7,
          "v8": structured_matvec_pallas_v8,
          "v9": structured_matvec_pallas_v9}[kernel_fn]
    model = make_cube_model(4, 3, 3, heterogeneous=True, seed=1)
    sp = partition_structured(model, 1)
    data = device_data_structured(sp, jnp.float32)
    blk = data["blocks"][0]
    ck0 = blk["ck"][0]
    ck_masked = ck0.at[:, :, -1].set(0.0)

    rng = np.random.default_rng(9)
    xg = jnp.asarray(rng.normal(size=(3, 5, 4, 4)), jnp.float32)
    y = fn(xg, ck_masked, blk["Ke"], interpret=True)
    # nodes on the far-z face only touch the zeroed cells via dz=1 corners;
    # recompute with the XLA path and compare
    ops = StructuredOps.from_partition(sp, dot_dtype=jnp.float32)
    data2 = {"blocks": [{**blk, "ck": ck_masked[None]}],
             **{k: v for k, v in data.items() if k != "blocks"}}
    y_ref = np.asarray(ops.matvec_local(
        data2, xg.reshape(1, -1)))[0]
    np.testing.assert_allclose(np.asarray(y).reshape(-1), y_ref,
                               rtol=2e-5, atol=2e-5)


def test_solver_pallas_interpret_structured_matches_xla():
    """SolverConfig.pallas='interpret' drives the REAL solver->kernel
    dispatch (grid reshape, leading-parts batching, f32 inner path)
    through the Pallas interpreter — the integration CI cannot get from
    kernel-level tests.  Must match the XLA path's iterations/solution."""
    from pcg_mpi_solver_tpu import RunConfig, SolverConfig
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver import Solver

    model = make_cube_model(8, 5, 4, heterogeneous=True, seed=6,
                            load="traction", load_value=1e6)
    res = {}
    for mode in ("off", "interpret"):
        cfg = RunConfig(solver=SolverConfig(
            tol=1e-6, max_iter=2000, dtype="float32",
            precision_mode="mixed", pallas=mode))
        s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1,
                   backend="structured")
        assert getattr(s.ops, "use_pallas", False) == (mode == "interpret")
        r = s.step(1.0)
        assert r.flag == 0, (mode, r)
        res[mode] = (int(r.iters), s.displacement_global())
    it_x, u_x = res["off"]
    it_p, u_p = res["interpret"]
    assert abs(it_x - it_p) <= 2, (it_x, it_p)
    # two f32 solves to tol=1e-6: agreement is bounded by the solver
    # tolerance times the solution scale, not by machine eps per element
    np.testing.assert_allclose(u_p, u_x, rtol=1e-3,
                               atol=1e-5 * float(np.abs(u_x).max()))


def test_solver_pallas_interpret_hybrid_matches_xla():
    """Same integration contract on the hybrid backend: the level-grid
    stencils route through batched_structured_matvec for every eligible
    level when pallas='interpret' (mirrors hybrid_pallas_enabled)."""
    from pcg_mpi_solver_tpu import RunConfig, SolverConfig
    from pcg_mpi_solver_tpu.models.octree import make_octree_model
    from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
    from pcg_mpi_solver_tpu.solver import Solver

    model = make_octree_model(3, 3, 3, max_level=2, n_incl=2, seed=5,
                              load="traction", load_value=1e6)
    res = {}
    for mode in ("off", "interpret"):
        cfg = RunConfig(solver=SolverConfig(
            tol=1e-6, max_iter=3000, dtype="float32",
            precision_mode="mixed", pallas=mode))
        s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1,
                   backend="hybrid")
        assert getattr(s.ops, "use_pallas", False) == (mode == "interpret")
        if mode == "interpret":
            assert any(s.ops.pallas_levels), s.ops.pallas_levels
        r = s.step(1.0)
        assert r.flag == 0, (mode, r)
        res[mode] = (int(r.iters), s.displacement_global())
    it_x, u_x = res["off"]
    it_p, u_p = res["interpret"]
    assert abs(it_x - it_p) <= 2, (it_x, it_p)
    np.testing.assert_allclose(u_p, u_x, rtol=1e-3,
                               atol=1e-5 * float(np.abs(u_x).max()))
