"""Nonlocal stress subsystem tests: weight builder vs a brute-force oracle,
device/host apply equivalence, and the end-to-end NS export variable
(reference config_NonlocalNeighbours, partition_mesh.py:1000-1299)."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.ops.nonlocal_stress import (
    apply_padded,
    build_nonlocal_weights,
    elem_stress_host,
    material_lc,
    nodal_average_host,
    von_mises_stress,
)


def _dense_oracle(model, ko=3.2):
    """Brute-force O(n^2) reconstruction of the reference weight rule."""
    lc = material_lc(model)
    ref_lc = ko * lc.max()
    vol = model.level**3
    n = model.n_elem
    W = np.zeros((n, n))
    for i in range(n):
        lc_i = lc[model.poly_mat[i]]
        for j in range(n):
            if model.poly_mat[j] != model.poly_mat[i]:
                continue
            d = model.sctrs[j] - model.sctrs[i]
            if np.max(np.abs(d)) > ref_lc:          # box window, not a ball
                continue
            r2 = float(d @ d)
            W[i, j] = np.exp(-0.5 * r2 / lc_i**2) * vol[j]
        W[i] /= W[i].sum()
    return W


@pytest.fixture(scope="module")
def het_model():
    """Two materials with DIFFERENT nonlocal lengths (left/right half)."""
    m = make_cube_model(5, 4, 3)
    m.poly_mat = (m.sctrs[:, 0] > 2.5).astype(np.int32)
    m.mat_prop = [
        {"E": 1.0, "Pos": 0.2, "Rho": 1.0, "NonLocStressParam": {"Lc": 2.0}},
        {"E": 10.0, "Pos": 0.2, "Rho": 1.0, "NonLocStressParam": {"Lc": 1.0}},
    ]
    return m


def test_weights_match_dense_oracle(het_model):
    nl = build_nonlocal_weights(het_model)
    assert len(np.unique(het_model.poly_mat)) == 2  # heterogeneity engaged
    W = nl.csr.toarray()
    np.testing.assert_allclose(W, _dense_oracle(het_model), rtol=1e-12, atol=1e-15)


def test_row_normalization_and_const_invariance(het_model):
    nl = build_nonlocal_weights(het_model)
    np.testing.assert_allclose(np.asarray(nl.csr.sum(axis=1)).ravel(), 1.0,
                               rtol=1e-12)
    c = nl.apply(np.full(het_model.n_elem, 7.5))
    np.testing.assert_allclose(c, 7.5, rtol=1e-12)


def test_padded_device_apply_matches_csr(het_model):
    import jax.numpy as jnp

    nl = build_nonlocal_weights(het_model)
    rng = np.random.default_rng(0)
    vals = rng.normal(size=het_model.n_elem)
    cols, w = nl.padded_arrays()
    got = np.asarray(apply_padded(jnp.asarray(cols), jnp.asarray(w),
                                  jnp.asarray(vals)))
    np.testing.assert_allclose(got, nl.apply(vals), rtol=1e-12)


def test_elem_stress_host_uniaxial():
    """A pure-stretch displacement field must give sigma = E*D(nu)[:,0]*eps
    in every element of a homogeneous block."""
    model = make_cube_model(3, 3, 3, E=200.0, nu=0.2)
    eps0 = 1e-3
    u = np.zeros(model.n_dof)
    u[0::3] = eps0 * model.node_coords[:, 0]   # u_x = eps0 * x
    sig = elem_stress_host(model, u)
    from pcg_mpi_solver_tpu.models.element import elasticity_matrix

    expect = 200.0 * elasticity_matrix(1.0, 0.2)[:, 0] * eps0
    np.testing.assert_allclose(
        sig, np.broadcast_to(expect, sig.shape), rtol=1e-10, atol=1e-12)

    vm = von_mises_stress(sig, axis=1)
    assert vm.shape == (model.n_elem,)
    assert np.all(vm > 0)

    nodal = nodal_average_host(model, vm)
    np.testing.assert_allclose(nodal, vm[0], rtol=1e-10)


def test_ns_export_end_to_end(tmp_path):
    from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
    from pcg_mpi_solver_tpu.solver.driver import Solver
    from pcg_mpi_solver_tpu.utils.io import RunStore

    model = make_cube_model(6, 4, 4, E=30e9, nu=0.2, load="traction",
                            load_value=1e6, heterogeneous=True)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0],
                                       export_vars="U NS"),
    )
    s = Solver(model, cfg)
    store = RunStore(str(tmp_path / "run"), "m")
    s.solve(store=store)

    ns = store.read_frame("NS", 1)
    node_map = store.read_map("NodeId")
    assert ns.shape == node_map.shape
    assert np.all(np.isfinite(ns)) and ns.max() > 0

    # oracle: direct host recomputation from the final solution
    from pcg_mpi_solver_tpu.ops.nonlocal_stress import build_nonlocal_weights

    nl = build_nonlocal_weights(model)
    sig = elem_stress_host(model, s.displacement_global())
    expect = nodal_average_host(model, nl.apply(von_mises_stress(sig, axis=1)))
    np.testing.assert_allclose(ns, expect[node_map], rtol=1e-8, atol=1e-3)
