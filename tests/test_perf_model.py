"""Analytic per-iteration cost model (obs/perf.py) + measured phase
attribution (obs/phases.py) — ISSUE 12.

The model side is pure python over the single-source ops tables, so the
full variant x precond enumeration is cheap to pin; the probe side is
exercised on a small CPU cube through the real Solver (same ops, same
shard_map programs) and through the ``pcg-tpu perf-report`` CLI verb.
"""

import json

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import (
    PCG_VARIANTS, PRECONDS, RunConfig, SolverConfig)
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.obs import perf
from pcg_mpi_solver_tpu.obs.schema import (
    BENCH_DETAIL_NUMERIC, validate_jsonl_text)
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.driver import Solver

#: multi-part synthetic geometry: collective terms engage (n_parts > 1),
#: element groups present so matvec flops/bytes come from the groups.
MP_SHAPE = perf.ProblemShape(n_dof=30_000, n_parts=8, n_iface=2_000,
                             elem_groups=((24, 9_000),),
                             mg_coarse_dofs=4_000)
SP_SHAPE = perf.ProblemShape(n_dof=30_000, n_parts=1,
                             elem_groups=((24, 9_000),))


# ---------------------------------------------------------------- model
def test_cost_model_every_combo_positive_and_complete():
    """The full canonical enumeration (the cost-model-completeness
    analysis rule proves the same totality in the lint tier): every
    variant x precond x nrhs entry has all four phases and a finite
    positive prediction."""
    table = perf.cost_model_table(MP_SHAPE, nrhs_set=(1, 8))
    assert len(table) == len(PCG_VARIANTS) * len(PRECONDS) * 2
    for (v, p, r), cm in table.items():
        assert tuple(cm["phases"]) == perf.PHASES, (v, p, r)
        pred = cm["predicted_ms_per_iter"]
        assert np.isfinite(pred) and pred > 0, (v, p, r, pred)
        assert pred == pytest.approx(
            sum(cm["phases"][ph]["model_ms"] for ph in perf.PHASES),
            rel=1e-6)


def test_single_part_has_no_collective_terms():
    for v in PCG_VARIANTS:
        costs = perf.phase_costs(SP_SHAPE, v, "jacobi")
        for ph, c in costs.items():
            assert c.coll_count == 0 and c.coll_bytes == 0, (v, ph)


def test_reduction_collectives_follow_variant_table():
    """The model's reduction-phase psum count IS the declared
    PCG_SCALAR_PSUMS row — classic's 3 serialized reductions vs the one
    fused/pipelined psum show up as collective latency the fused
    variants don't pay."""
    from pcg_mpi_solver_tpu.ops.matvec import (
        PCG_SCALAR_PSUMS, PCG_VECTOR_AXPYS)

    for v in PCG_VARIANTS:
        costs = perf.phase_costs(MP_SHAPE, v, "jacobi")
        assert costs["reduction"].coll_count == PCG_SCALAR_PSUMS[v], v
        # axpy flops scale with the declared vector-update count
        assert costs["axpy"].flops == pytest.approx(
            2.0 * MP_SHAPE.n_dof * PCG_VECTOR_AXPYS[v])
    classic = perf.phase_costs(MP_SHAPE, "classic", "jacobi")["reduction"]
    fused = perf.phase_costs(MP_SHAPE, "fused", "jacobi")["reduction"]
    assert classic.coll_count > fused.coll_count


def test_unknown_variant_and_precond_raise_keyerror():
    """The single-source-table loudness contract: an out-of-sync name
    must never model as a silent default row."""
    with pytest.raises(KeyError):
        perf.phase_costs(MP_SHAPE, "no_such_variant", "jacobi")
    with pytest.raises(KeyError):
        perf.phase_costs(MP_SHAPE, "classic", "no_such_precond")
    with pytest.raises(KeyError):
        perf.cost_model(MP_SHAPE, "classic", "no_such_precond")


def test_nrhs_widens_memory_bound_phases_linearly():
    one = perf.phase_costs(MP_SHAPE, "fused", "jacobi", nrhs=1)
    eight = perf.phase_costs(MP_SHAPE, "fused", "jacobi", nrhs=8)
    for ph in perf.PHASES:
        assert eight[ph].flops == pytest.approx(8 * one[ph].flops)
        assert eight[ph].hbm_bytes == pytest.approx(8 * one[ph].hbm_bytes)
    # psum COUNT does not grow with the block width (payload does)
    assert eight["reduction"].coll_count == one["reduction"].coll_count
    assert eight["reduction"].coll_bytes == pytest.approx(
        8 * one["reduction"].coll_bytes)


def test_mg_predicts_costlier_iterations_than_jacobi():
    """The V-cycle's extra fine matvecs must show up in the precond
    phase — an mg iteration that models cheaper than jacobi would
    invert every measured A/B in the repo."""
    for v in PCG_VARIANTS:
        mg = perf.cost_model(MP_SHAPE, v, "mg")
        ja = perf.cost_model(MP_SHAPE, v, "jacobi")
        assert mg["phases"]["precond"]["model_ms"] > \
            3 * ja["phases"]["precond"]["model_ms"]
        assert mg["predicted_ms_per_iter"] > ja["predicted_ms_per_iter"]


def test_resolve_profile_platform_and_env_overrides(monkeypatch):
    assert perf.resolve_profile("cpu").name == "cpu"
    assert perf.resolve_profile("CPU (x86)").name == "cpu"
    assert perf.resolve_profile("TPU v4").name == "tpu"
    assert perf.resolve_profile("tpu").name == "tpu"
    monkeypatch.setenv("PCG_TPU_ROOFLINE_HBM_GBS", "123")
    monkeypatch.setenv("PCG_TPU_ROOFLINE_COLL_LAT_US", "7")
    prof = perf.resolve_profile("tpu")
    assert prof.hbm_bytes_per_s == pytest.approx(123e9)
    assert prof.coll_latency_s == pytest.approx(7e-6)
    # overridden HBM rate must move a memory-bound prediction
    base = perf.cost_model(SP_SHAPE, "classic", "jacobi",
                           profile=perf.HW_PROFILES["tpu"])
    fast = perf.cost_model(SP_SHAPE, "classic", "jacobi", profile=prof)
    assert fast["predicted_ms_per_iter"] != \
        base["predicted_ms_per_iter"]


def test_bench_detail_schema_covers_model_fields():
    assert "predicted_ms_per_iter" in BENCH_DETAIL_NUMERIC
    assert "model_ratio" in BENCH_DETAIL_NUMERIC


def test_bench_line_prediction_from_detail_fields():
    """bench._predict_ms_per_iter builds the model from a line's OWN
    detail dict (salvage lines have no live solver): known combo ->
    positive number, no dofs -> null, unknown variant -> loud
    KeyError."""
    from pcg_mpi_solver_tpu.bench import _predict_ms_per_iter

    detail = {"n_dof": 3_000_000, "n_parts": 8, "backend": "structured",
              "mode": "mixed", "dtype": "float64", "platform": "TPU v6e",
              "pcg_variant": "fused", "precond": "jacobi", "nrhs": 1}
    pred = _predict_ms_per_iter(detail)
    assert pred and np.isfinite(pred) and pred > 0
    assert _predict_ms_per_iter({**detail, "n_dof": 0}) is None
    with pytest.raises(KeyError):
        _predict_ms_per_iter({**detail, "pcg_variant": "mislabeled"})


# ---------------------------------------------------------------- probes
@pytest.fixture(scope="module")
def probed_solver(tmp_path_factory):
    """One small heterogeneous cube Solver with a telemetry JSONL sink —
    shared by the probe tests (construction emits the cost_model
    event)."""
    out = str(tmp_path_factory.mktemp("perf") / "run.jsonl")
    model = make_cube_model(8, 0, 0, E=30e9, nu=0.2, load="traction",
                            load_value=1e6, heterogeneous=True)
    cfg = RunConfig(telemetry_path=out,
                    solver=SolverConfig(tol=1e-8, max_iter=400))
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1,
               backend="general")
    return s, out


def _events(path):
    text = open(path).read()
    assert validate_jsonl_text(text) == []
    return [json.loads(ln) for ln in text.splitlines()]


def test_solver_emits_cost_model_event_and_gauges(probed_solver):
    s, out = probed_solver
    assert s._cost_model is not None
    events = [e for e in _events(out) if e["kind"] == "cost_model"]
    assert len(events) == 1
    cm = events[0]
    assert cm["pcg_variant"] == "classic" and cm["precond"] == "jacobi"
    assert cm["backend"] == s.backend
    assert tuple(cm["phases"]) == perf.PHASES
    assert cm["predicted_ms_per_iter"] > 0
    assert cm["predicted_ms_per_iter"] == \
        s._cost_model["predicted_ms_per_iter"]
    assert s.recorder.gauges["perf.predicted_ms_per_iter"] == \
        cm["predicted_ms_per_iter"]
    # the derived geometry reflects the real model
    assert s._perf_shape.n_dof == s.pm.glob_n_dof
    assert s._perf_shape.elem_groups, "element groups not derived"


def test_solver_degrades_on_shape_derivation_keyerror(
        tmp_path, monkeypatch):
    """The loud-KeyError contract belongs to the cost_model() name
    tables ONLY: a KeyError thrown by shape derivation (e.g. a refactor
    that switches a getattr to dict indexing) must degrade to a note,
    not abort Solver construction — observability is not a solve
    dependency."""
    from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig
    from pcg_mpi_solver_tpu.solver.driver import Solver

    def boom(_s):
        raise KeyError("some_internal_field")

    monkeypatch.setattr(perf, "shape_from_solver", boom)
    model = make_cube_model(4, 0, 0, E=30e9, nu=0.2, load="traction",
                            load_value=1e6, heterogeneous=True)
    out = str(tmp_path / "t.jsonl")
    s = Solver(model, RunConfig(solver=SolverConfig(tol=1e-6),
                                telemetry_path=out),
               mesh=make_mesh(1), n_parts=1, backend="general")
    assert s._cost_model is None and s._perf_shape is None
    notes = [e for e in _events(out) if e["kind"] == "note"]
    assert any("cost_model unavailable" in str(e.get("msg", ""))
               for e in notes), notes


def test_phase_probe_sum_approximates_whole_iteration(probed_solver):
    """The acceptance shape on the CPU golden model: four positive
    measured phases whose sum lands in the same regime as the real
    whole-iteration time.  The band is deliberately generous (the CI
    container is shared and this cube is small); `pcg-tpu perf-report`
    at its default size is the calibrated surface."""
    from pcg_mpi_solver_tpu.obs.phases import run_phase_probe

    s, out = probed_solver
    payload = run_phase_probe(s, reps=2, inner=8)
    assert tuple(payload["phases"]) == perf.PHASES
    assert all(v > 0 for v in payload["phases"].values())
    assert payload["sum_ms_per_iter"] == pytest.approx(
        sum(payload["phases"].values()), rel=1e-6)
    assert payload["whole_ms_per_iter"] > 0
    assert payload["whole_iters"] >= 1
    assert 0.25 < payload["attribution"] < 3.0, payload
    # emitted as a schema-valid phase_probe event with perf gauges
    events = [e for e in _events(out) if e["kind"] == "phase_probe"]
    assert events and events[-1]["sum_ms_per_iter"] == \
        payload["sum_ms_per_iter"]
    assert s.recorder.gauges["perf.measured.matvec_ms"] == \
        payload["phases"]["matvec"]


def test_phase_probe_counts_no_extra_collectives(probed_solver):
    """Probe fidelity: the reduction program must execute the VARIANT's
    declared psum count — the trace-level proof is the jaxpr psum count
    of the built reduction program on a 2-part mesh."""
    import jax

    from pcg_mpi_solver_tpu.analysis.jaxpr_utils import (
        collective_histogram)
    from pcg_mpi_solver_tpu.obs.phases import PhaseProbe
    from pcg_mpi_solver_tpu.ops.matvec import PCG_SCALAR_PSUMS

    s, _ = probed_solver
    probe = PhaseProbe(s, inner=4)
    probe._build()
    jaxpr = jax.make_jaxpr(probe._progs["reduction"])(s.data)
    # the fori_loop body traces ONCE, so the histogram is exactly the
    # per-iteration-equivalent collective count the phase quotes
    assert collective_histogram(jaxpr).get("psum", 0) == \
        PCG_SCALAR_PSUMS["classic"]


def test_phase_probe_rejects_mixed_mode():
    from pcg_mpi_solver_tpu.obs.phases import PhaseProbe

    model = make_cube_model(4, 0, 0, E=30e9, nu=0.2, load="traction",
                            load_value=1e6, heterogeneous=True)
    cfg = RunConfig(solver=SolverConfig(tol=1e-8, precision_mode="mixed"))
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1,
               backend="general")
    with pytest.raises(ValueError, match="direct-mode"):
        PhaseProbe(s)


def test_perf_report_cli_end_to_end(tmp_path, capsys):
    """The acceptance verb: `pcg-tpu perf-report` on a CPU golden solve
    prints the measured-vs-model table for all four phases, the
    whole-iteration anchor and the attribution ratio, and leaves a
    schema-valid telemetry stream carrying cost_model + phase_probe."""
    from pcg_mpi_solver_tpu.cli import main

    out = str(tmp_path / "perf.jsonl")
    main(["perf-report", "--nx", "8", "--reps", "1", "--inner", "6",
          "--telemetry-out", out])
    stdout = capsys.readouterr().out
    for ph in perf.PHASES:
        assert f"\n{ph}" in stdout, stdout
    assert ">whole-iteration anchor:" in stdout
    assert ">attribution (phase sum / whole):" in stdout
    assert ">model ratio (measured whole / predicted):" in stdout
    kinds = [e["kind"] for e in _events(out)]
    assert "cost_model" in kinds and "phase_probe" in kinds


def test_perf_report_cli_measured_only_when_model_degrades(
        tmp_path, capsys, monkeypatch):
    """When the cost-model derivation raises on an exotic model the
    Solver degrades to _cost_model=None with a note; perf-report must
    then print the MEASURED-only table instead of re-raising the same
    exception through its fallback recompute."""
    from pcg_mpi_solver_tpu.cli import main

    def boom(*a, **k):
        raise RuntimeError("synthetic cost-model failure")

    monkeypatch.setattr(perf, "cost_model", boom)
    main(["perf-report", "--nx", "8", "--reps", "1", "--inner", "6"])
    stdout = capsys.readouterr().out
    assert ">cost model unavailable (RuntimeError: synthetic " \
           "cost-model failure) — measured-only table" in stdout
    for ph in perf.PHASES:
        assert f"\n{ph}" in stdout, stdout       # measured rows printed
    # every model cell AND the sum print '-' — never a fabricated 0.0000
    table = [ln for ln in stdout.splitlines()
             if ln.split(" ")[0] in perf.PHASES + ("sum",)]
    assert len(table) == 5 and all(ln.split()[1] == "-" for ln in table)
    assert ">whole-iteration anchor:" in stdout
    assert ">model ratio" not in stdout          # no model to compare
