"""Export pipeline: run store, frames, VTU writing/readback, end-to-end."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.synthetic import make_cube_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.driver import Solver
from pcg_mpi_solver_tpu.utils.io import RunStore
from pcg_mpi_solver_tpu.vtk.export import export_vtk
from pcg_mpi_solver_tpu.vtk.writer import read_vtu_arrays, write_vtu, VTK_QUAD


def test_vtu_roundtrip(tmp_path):
    pts = np.array([[0, 0, 0], [1, 0, 0], [1, 1, 0], [0, 1, 0]], float)
    conn = np.array([0, 1, 2, 3])
    offs = np.array([4])
    path = write_vtu(str(tmp_path / "quad"), pts, conn, offs,
                     np.array([VTK_QUAD]),
                     point_data={"T": np.array([1.0, 2.0, 3.0, 4.0]),
                                 "U": (pts[:, 0], pts[:, 1], pts[:, 2])})
    arrs = read_vtu_arrays(path)
    np.testing.assert_allclose(arrs["Points"], pts)
    np.testing.assert_array_equal(arrs["connectivity"], conn)
    np.testing.assert_allclose(arrs["T"], [1, 2, 3, 4])
    assert arrs["U"].shape == (4, 3)


def test_vtu_paraview_header(tmp_path):
    """File begins with a valid VTKFile XML declaration ParaView accepts."""
    pts = np.zeros((3, 3))
    path = write_vtu(str(tmp_path / "t"), pts, np.array([0, 1, 2]),
                     np.array([3]), np.array([5]))
    head = open(path, "rb").read(200)
    assert b"<VTKFile type=" in head and b"UnstructuredGrid" in head


def test_solve_with_export_roundtrip(tmp_path):
    """Full pipeline: solve -> store frames -> reassemble global U."""
    model = make_cube_model(4, 4, 4, load="dirichlet")
    cfg = RunConfig(
        scratch_path=str(tmp_path), run_id="7",
        solver=SolverConfig(tol=1e-9, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 0.5, 1.0],
                                       export_frame_rate=1),
    )
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    store = RunStore(cfg.result_path, cfg.model_name)
    s.solve(store=store)

    # Dof map covers every dof exactly once
    dof_map = store.read_map("Dof")
    assert sorted(dof_map) == list(range(model.n_dof))
    # 3 frames: initial state + 2 steps
    assert store.n_frames("U") == 3
    u2 = np.zeros(model.n_dof)
    u2[dof_map] = store.read_frame("U", 2)
    np.testing.assert_allclose(u2, s.displacement_global(), rtol=1e-12)
    # frame 1 at delta=0.5 is half of frame 2 (linear problem)
    u1 = np.zeros(model.n_dof)
    u1[dof_map] = store.read_frame("U", 1)
    np.testing.assert_allclose(u1, 0.5 * u2, rtol=1e-5, atol=1e-10)
    # time data recorded
    td = store.read_time_data(4)
    assert list(td["Flag"]) == [0, 0] and len(td["Iter"]) == 2

    # VTK export (Full + MidSlices)
    files = export_vtk(model, store, ["U"], "Full")
    assert len(files) == 3
    arrs = read_vtu_arrays(files[2])
    assert arrs["U"].shape == (model.n_node, 3)
    np.testing.assert_allclose(arrs["U"].ravel(),
                               u2.reshape(-1, 3).ravel(), rtol=1e-6)
    files_mid = export_vtk(model, store, ["U"], "MidSlices")
    assert len(files_mid) == 3


def test_boundary_mode_differs_from_full_on_octree(tmp_path):
    """Real Boundary mode (face-incidence counting, export_vtk.py:105-113):
    the octree model stores EVERY element face, so Full includes interior
    faces and Boundary must be a strict subset (VERDICT round 1, missing #2)."""
    from pcg_mpi_solver_tpu.models.octree import make_octree_model
    from pcg_mpi_solver_tpu.vtk.export import _select_faces

    model = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3)
    full = _select_faces(model, "Full")
    bnd = _select_faces(model, "Boundary")
    assert 0 < len(bnd) < len(full)
    # every boundary face has all nodes on the domain hull OR is a
    # coarse/fine mismatch face... for this conforming face list, incidence-1
    # quads must lie on the axis-aligned hull:
    coords = model.node_coords
    flat, offset = model.faces_flat, model.faces_offset
    hull = np.zeros(len(coords), dtype=bool)
    for ax in range(3):
        hull |= (np.abs(coords[:, ax] - coords[:, ax].min()) < 1e-12)
        hull |= (np.abs(coords[:, ax] - coords[:, ax].max()) < 1e-12)
    for f in bnd[:50]:
        nodes = flat[offset[f]:offset[f + 1]]
        assert hull[nodes].all()

    # end-to-end: solve 1 step, export Boundary, check vtu face count
    cfg = RunConfig(
        scratch_path=str(tmp_path), run_id="oct",
        solver=SolverConfig(tol=1e-7, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]),
    )
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4)
    store = RunStore(cfg.result_path, cfg.model_name)
    s.solve(store=store)
    # (both modes write the same frame filenames — read each before the next)
    files_b = export_vtk(model, store, ["U"], "Boundary")
    nb = len(read_vtu_arrays(files_b[0])["offsets"])
    files_f = export_vtk(model, store, ["U"], "Full")
    nf = len(read_vtu_arrays(files_f[0])["offsets"])
    assert nb == len(bnd) and nf == len(full) and nb < nf


def test_frame_pool_matches_serial(tmp_path):
    """The multiprocessing frame pool produces byte-identical .vtu files to
    the serial loop."""
    model = make_cube_model(3, 3, 3, load="dirichlet")
    cfg = RunConfig(
        scratch_path=str(tmp_path), run_id="pool",
        solver=SolverConfig(tol=1e-9, max_iter=1000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 0.25, 0.5, 1.0],
                                       export_frame_rate=1),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    store = RunStore(cfg.result_path, cfg.model_name)
    s.solve(store=store)
    serial = export_vtk(model, store, ["U"], "Full")
    blobs = [open(f, "rb").read() for f in serial]
    pooled = export_vtk(model, store, ["U"], "Full", n_workers=3)
    assert pooled == serial
    for f, blob in zip(pooled, blobs):
        assert open(f, "rb").read() == blob


def test_existing_run_dir_renamed(tmp_path):
    store = RunStore(str(tmp_path / "Results_Run1"), "m")
    store.prepare()
    store.write_map("Dof", np.arange(3))
    store2 = RunStore(str(tmp_path / "Results_Run1"), "m")
    store2.prepare()  # must not clobber; old dir renamed with timestamp
    import glob
    assert len(glob.glob(str(tmp_path / "Results_Run1_*"))) == 1


def test_probe_dof_history(tmp_path):
    model = make_cube_model(3, 3, 3, load="traction")
    probe = [3 * (model.n_node - 1)]  # ux of the last node
    cfg = RunConfig(
        scratch_path=str(tmp_path), run_id="2",
        solver=SolverConfig(tol=1e-9, max_iter=2000),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 0.25, 1.0],
                                       plot_flag=True, probe_dofs=probe),
    )
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    store = RunStore(cfg.result_path, cfg.model_name)
    s.solve(store=store)
    import numpy as np
    dat = np.load(f"{store.plot_path}/{cfg.model_name}_PlotData.npz",
                  allow_pickle=True)["PlotData"].item()
    u_hist = dat["Plot_U"]
    assert u_hist.shape == (1, 2)
    np.testing.assert_allclose(u_hist[0, 0] * 4.0, u_hist[0, 1], rtol=1e-5)


def test_frame_shard_validation(tmp_path):
    """read_frame must reject stale, incomplete, or mixed-generation
    shard sets instead of merging them into a garbled frame."""
    import pytest

    from pcg_mpi_solver_tpu.utils.io import RunStore

    store = RunStore(str(tmp_path / "res"))
    store.prepare()
    store.write_frame_shard("U", 0, np.arange(3.0), 0, 4, 8)
    # incomplete: missing parts 4..8
    with pytest.raises(ValueError, match="incomplete"):
        store.read_frame("U", 0)
    store.write_frame_shard("U", 0, np.arange(2.0), 4, 8, 8)
    np.testing.assert_array_equal(store.read_frame("U", 0),
                                  [0.0, 1.0, 2.0, 0.0, 1.0])
    assert store.n_frames("U") == 1
    # mixed generation: stale shard from an older 4-part layout
    store.write_frame_shard("U", 0, np.arange(1.0), 0, 2, 4)
    with pytest.raises(ValueError, match="mixed-generation"):
        store.read_frame("U", 0)


def test_frame_shard_gap_detected(tmp_path):
    import pytest

    from pcg_mpi_solver_tpu.utils.io import RunStore

    store = RunStore(str(tmp_path / "res"))
    store.prepare()
    store.write_frame_shard("U", 1, np.arange(3.0), 0, 2, 6)
    store.write_frame_shard("U", 1, np.arange(2.0), 4, 6, 6)
    with pytest.raises(ValueError, match="tile contiguously"):
        store.read_frame("U", 1)


def test_backend_probe_skips():
    """The probe must not spawn subprocesses when it cannot add info."""
    import os

    from pcg_mpi_solver_tpu.utils.backend_probe import (backend_live,
                                                        probe_backend)

    # conftest pins JAX_PLATFORMS=cpu for the test session
    assert os.environ.get("JAX_PLATFORMS", "").lower() == "cpu"
    ok, detail = probe_backend(timeout_s=1.0)
    assert ok and "skipped" in detail
    # jax is live in the test process by now
    assert backend_live()
