"""Fast artifact lint (tier-1): every committed BENCH_*.json round
artifact — and any telemetry JSONL the tree carries — must validate
against the versioned schemas in obs/schema.py, via the same
tools/check_telemetry_schema.py entry point CI and humans run."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_telemetry_schema.py")

sys.path.insert(0, os.path.join(REPO, "tools"))
import check_telemetry_schema as lint  # noqa: E402


def test_committed_bench_artifacts_validate():
    paths = lint.default_paths()
    assert paths, "expected committed BENCH_*.json artifacts at repo root"
    errors = []
    for p in paths:
        errors.extend(lint.check_file(p))
    assert errors == []


def test_tool_cli_exit_codes(tmp_path):
    ok = subprocess.run([sys.executable, TOOL], capture_output=True,
                        text=True, cwd=REPO)
    assert ok.returncode == 0, ok.stderr

    bad = tmp_path / "BENCH_bad.json"
    bad.write_text(json.dumps({"metric": "m", "value": "not-a-number"}))
    r = subprocess.run([sys.executable, TOOL, str(bad)],
                       capture_output=True, text=True)
    assert r.returncode == 1
    assert "value" in r.stderr


def test_tool_accepts_failed_round_wrapper(tmp_path):
    """BENCH_r01..r03 shape: the driver captured a crash (rc != 0,
    parsed null) — a legitimate artifact, not a schema violation."""
    p = tmp_path / "BENCH_failed.json"
    p.write_text(json.dumps({"n": 1, "cmd": "python bench.py", "rc": 1,
                             "tail": "Traceback ...", "parsed": None}))
    assert lint.check_file(str(p)) == []
    # but a wrapper claiming SUCCESS with no payload is an error
    p.write_text(json.dumps({"n": 1, "cmd": "python bench.py", "rc": 0,
                             "tail": "", "parsed": None}))
    assert lint.check_file(str(p)) != []


def test_tool_validates_jsonl(tmp_path):
    from pcg_mpi_solver_tpu.obs.schema import TELEMETRY_SCHEMA

    p = tmp_path / "run.jsonl"
    good = {"schema": TELEMETRY_SCHEMA, "t": 0.0, "kind": "note", "msg": "x"}
    p.write_text(json.dumps(good) + "\n")
    assert lint.check_file(str(p)) == []
    p.write_text(json.dumps({"kind": "note"}) + "\nnot json\n")
    errs = lint.check_file(str(p))
    assert len(errs) >= 2


def test_setup_detail_fields_validate():
    """Warm-path bench fields (cache/ subsystem): numeric-or-null
    setup_s/time_to_first_iter_s and the off/cold/warm setup_cache enum
    are enforced WHEN present; absent fields (pre-warm-path committed
    artifacts) stay valid — exercised above on the real BENCH_r0*.json."""
    from pcg_mpi_solver_tpu.obs.schema import validate_bench_line

    base = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0}
    ok = dict(base, detail={"setup_s": 1.5, "setup_cache": "cold",
                            "time_to_first_iter_s": None})
    assert validate_bench_line(ok) == []
    assert validate_bench_line(dict(base, detail={})) == []
    bad_num = dict(base, detail={"setup_s": "1.5s"})
    assert any("setup_s" in e for e in validate_bench_line(bad_num))
    bad_ttfi = dict(base, detail={"time_to_first_iter_s": "soon"})
    assert any("time_to_first_iter_s" in e
               for e in validate_bench_line(bad_ttfi))
    bad_enum = dict(base, detail={"setup_cache": "lukewarm"})
    assert any("setup_cache" in e for e in validate_bench_line(bad_enum))


def test_current_bench_line_is_schema_valid():
    """The line bench.py emits TODAY must satisfy the schema the lint
    enforces (catches drift between emitter and validator)."""
    from pcg_mpi_solver_tpu.bench import _error_line
    from pcg_mpi_solver_tpu.obs.schema import validate_bench_line

    assert validate_bench_line(json.loads(_error_line("x"))) == []
