"""Hybrid level-grid backend vs the general node-ELL path.

Both backends share partition_model's local numbering (block_filter only
removes brick elements from the type blocks, not from the local sets), so
operator outputs are directly comparable part-by-part."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
from pcg_mpi_solver_tpu.models.octree import make_octree_model
from pcg_mpi_solver_tpu.ops.matvec import Ops, device_data
from pcg_mpi_solver_tpu.parallel import make_mesh
from pcg_mpi_solver_tpu.parallel.hybrid import (
    HybridOps, device_data_hybrid, partition_hybrid)
from pcg_mpi_solver_tpu.parallel.partition import make_elem_part, partition_model
from pcg_mpi_solver_tpu.solver import Solver


@pytest.fixture(scope="module")
def model():
    return make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3,
                             load="traction", load_value=1.0)


@pytest.fixture(scope="module", params=[1, 4])
def pair(model, request):
    """(general ops+data, hybrid ops+data) on the SAME partition."""
    P = request.param
    ep = make_elem_part(model, P, method="rcb")
    pm_g = partition_model(model, P, elem_part=ep)
    ops_g = Ops.from_model(pm_g)
    data_g = device_data(pm_g)
    hp = partition_hybrid(model, P, elem_part=ep)
    ops_h = HybridOps.from_hybrid(hp)
    data_h = device_data_hybrid(hp)
    return (ops_g, data_g), (ops_h, data_h), pm_g, hp


def test_brick_metadata(model):
    meta = model.octree
    assert meta["brick_type"] is not None
    from pcg_mpi_solver_tpu.models.element import HEX_CORNERS

    np.testing.assert_array_equal(meta["brick_corners"],
                                  HEX_CORNERS.astype(np.int64))
    # a graded octree is mostly bricks
    n_brick = int((model.elem_type == meta["brick_type"]).sum())
    assert n_brick > 0.5 * model.n_elem


def test_hybrid_blocks_shrunk(pair):
    _, (ops_h, data_h), pm_g, hp = pair
    n_gen = sum(int(tb.n_elem.sum()) for tb in pm_g.type_blocks)
    n_hyb = sum(int(tb.n_elem.sum()) for tb in hp.pm.type_blocks)
    n_grid = sum(int(lv.n_cells.sum()) for lv in hp.levels)
    assert n_hyb + n_grid == n_gen
    assert n_grid > 0


def test_matvec_matches_general(pair):
    (ops_g, data_g), (ops_h, data_h), pm_g, hp = pair
    P = pm_g.n_parts
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(P, pm_g.n_loc)))
    yg = np.asarray(ops_g.matvec(data_g, x))
    yh = np.asarray(ops_h.matvec(data_h, x))
    scale = np.abs(yg).max()
    np.testing.assert_allclose(yh, yg, rtol=0, atol=1e-12 * scale)


def test_diag_matches_general(pair):
    (ops_g, data_g), (ops_h, data_h), pm_g, hp = pair
    dg = np.asarray(ops_g.diag(data_g))
    dh = np.asarray(ops_h.diag(data_h))
    np.testing.assert_allclose(dh, dg, rtol=0, atol=1e-12 * np.abs(dg).max())


def test_nodal_average_matches_general(pair):
    (ops_g, data_g), (ops_h, data_h), pm_g, hp = pair
    P = pm_g.n_parts
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(P, pm_g.n_loc)))
    eg = ops_g.elem_strain(data_g, x)
    eh = ops_h.elem_strain(data_h, x)
    ag = np.asarray(ops_g.nodal_average(data_g, eg))
    ah = np.asarray(ops_h.nodal_average(data_h, eh))
    scale = max(np.abs(ag).max(), 1e-30)
    np.testing.assert_allclose(ah, ag, rtol=0, atol=1e-11 * scale)


def test_solve_matches_general(model):
    """Full quasi-static solve: identical iteration count and solution."""
    results = {}
    for backend in ("general", "hybrid"):
        cfg = RunConfig(
            solver=SolverConfig(tol=1e-9, max_iter=3000, dtype="float64"),
            time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]))
        s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4, backend=backend)
        assert s.backend == backend
        res = s.step(1.0)
        assert res.flag == 0
        results[backend] = (res.iters, s.displacement_global())
    ig, ug = results["general"]
    ih, uh = results["hybrid"]
    assert abs(ig - ih) <= 1, (ig, ih)
    np.testing.assert_allclose(uh, ug, rtol=0,
                               atol=1e-9 * np.abs(ug).max())


def _strip_fastpath_meta(model):
    import copy

    m = copy.deepcopy(model)
    m.octree = None
    m.grid = None
    return m


def test_reconstruct_octree_meta_roundtrip(model, monkeypatch):
    monkeypatch.setenv("PCG_TPU_ENABLE_HYBRID", "1")
    """A bundle WITHOUT the Octree.npz sidecar (a genuine reference
    bundle) must reconstruct lattice metadata from pure geometry and
    route to the hybrid backend with iteration parity vs the general
    path (VERDICT r03 weakness 3)."""
    from pcg_mpi_solver_tpu.models.octree import reconstruct_lattice_meta

    m = _strip_fastpath_meta(model)
    assert reconstruct_lattice_meta(m)
    ot, ref = m.octree, model.octree
    assert ot["brick_type"] == ref["brick_type"]
    assert ot["dims"] == ref["dims"]
    assert ot["strides"] == ref["strides"]
    np.testing.assert_array_equal(ot["leaves"], ref["leaves"])
    np.testing.assert_array_equal(ot["node_keys"], ref["node_keys"])
    np.testing.assert_array_equal(ot["brick_corners"], ref["brick_corners"])

    # end to end: auto backend prefers hybrid on the reconstructed model
    s = Solver(m, RunConfig(), mesh=make_mesh(4), n_parts=4)
    assert s.backend == "hybrid"
    res = s.step(1.0)
    sg = Solver(model, RunConfig(), mesh=make_mesh(4), n_parts=4,
                backend="general")
    rg = sg.step(1.0)
    assert res.flag == 0 and abs(int(res.iters) - int(rg.iters)) <= 1
    np.testing.assert_allclose(
        s.displacement_global(), sg.displacement_global(), rtol=0,
        atol=1e-9 * np.abs(sg.displacement_global()).max())


def test_reconstruct_handles_arbitrary_node_numbering(model):
    """Reconstruction + partition must not assume sorted-key node
    numbering: permute the node ids of the octree model and check the
    hybrid solve still matches the general backend exactly."""
    import copy

    m = copy.deepcopy(model)
    rng = np.random.default_rng(7)
    perm = rng.permutation(m.n_node)         # new id of old node i
    inv = np.argsort(perm)
    m.node_coords = m.node_coords[inv]
    old_dofs = np.asarray(m.elem_dofs_flat)
    m.elem_dofs_flat = 3 * perm[old_dofs // 3] + old_dofs % 3
    m.elem_nodes_flat = perm[m.elem_nodes_flat]
    dof_perm = (3 * perm[:, None] + np.arange(3)[None]).ravel()
    dof_inv = np.argsort(dof_perm)
    for name in ("F", "Ud", "Vd", "diag_M"):
        setattr(m, name, getattr(m, name)[dof_inv])
    m.fixed_dof = np.sort(dof_perm[m.fixed_dof])
    m.dof_eff = np.sort(dof_perm[m.dof_eff])
    m.faces_flat = perm[m.faces_flat]
    m.octree = None
    m.grid = None
    from pcg_mpi_solver_tpu.models.octree import reconstruct_lattice_meta

    assert reconstruct_lattice_meta(m)
    # node_keys now follow the permuted numbering (NOT sorted)
    assert not np.all(np.diff(m.octree["node_keys"]) > 0)
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-9, max_iter=3000, dtype="float64"),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]))
    sh = Solver(m, cfg, mesh=make_mesh(4), n_parts=4, backend="hybrid")
    rh = sh.step(1.0)
    sg = Solver(m, cfg, mesh=make_mesh(4), n_parts=4, backend="general")
    rg = sg.step(1.0)
    assert rh.flag == 0 and abs(int(rh.iters) - int(rg.iters)) <= 1
    np.testing.assert_allclose(
        sh.displacement_global(), sg.displacement_global(), rtol=0,
        atol=1e-9 * np.abs(sg.displacement_global()).max())


def test_reconstruct_declines_foreign_corner_order(model):
    """A bundle whose hex connectivity uses a valid but DIFFERENT corner
    order must decline (stay on the general path) — engaging would crash
    partition_hybrid's _CORNERS assertion (r04 review finding)."""
    import copy

    from pcg_mpi_solver_tpu.models.octree import reconstruct_lattice_meta

    m = copy.deepcopy(model)
    bt = m.octree["brick_type"]
    m.octree = None
    m.grid = None
    sel = np.where(m.elem_type == bt)[0]
    perm8 = np.array([0, 2, 1, 3, 4, 6, 5, 7])      # consistent, non-canon
    idx = m.elem_nodes_offset[sel, None] + perm8[None]
    m.elem_nodes_flat[m.elem_nodes_offset[sel, None] + np.arange(8)[None]] \
        = m.elem_nodes_flat[idx].copy()
    didx = (m.elem_dofs_offset[sel, None, None]
            + 3 * perm8[None, :, None] + np.arange(3)[None, None])
    base = (m.elem_dofs_offset[sel, None, None]
            + 3 * np.arange(8)[None, :, None] + np.arange(3)[None, None])
    m.elem_dofs_flat[base.reshape(len(sel), -1)] = \
        m.elem_dofs_flat[didx.reshape(len(sel), -1)].copy()
    assert not reconstruct_lattice_meta(m)
    assert m.octree is None


def test_reconstruct_declines_non_lattice(model):
    """Perturbed geometry must leave the model on the general path."""
    from pcg_mpi_solver_tpu.models.octree import reconstruct_lattice_meta

    m = _strip_fastpath_meta(model)
    m.node_coords = m.node_coords + 0.01 * np.sin(
        np.arange(m.node_coords.size)).reshape(m.node_coords.shape)
    assert not reconstruct_lattice_meta(m)
    assert m.octree is None and m.grid is None


def test_merged_levels_match_unmerged(model):
    """PCG_TPU_HYBRID_MERGE (default OFF: measured compile-negative,
    docs/BENCH_LOG.md) folds all level grids into ONE
    block batch — the matvec, diagonal, node blocks and strain must be
    identical to the per-level layout, and the merged partition must
    carry exactly one level."""
    import os

    from pcg_mpi_solver_tpu.parallel.partition import make_elem_part

    ep = make_elem_part(model, 2, method="rcb")
    prev = os.environ.get("PCG_TPU_HYBRID_MERGE")
    try:
        os.environ["PCG_TPU_HYBRID_MERGE"] = "0"
        hp_u = partition_hybrid(model, 2, elem_part=ep)
        os.environ["PCG_TPU_HYBRID_MERGE"] = "1"
        hp_m = partition_hybrid(model, 2, elem_part=ep)
    finally:
        if prev is None:
            os.environ.pop("PCG_TPU_HYBRID_MERGE", None)
        else:
            os.environ["PCG_TPU_HYBRID_MERGE"] = prev
    assert len(hp_u.levels) > 1
    assert len(hp_m.levels) == 1 and hp_m.levels[0].size == 0
    assert (sum(int(lv.n_cells.sum()) for lv in hp_u.levels)
            == int(hp_m.levels[0].n_cells.sum()))
    ops_u = HybridOps.from_hybrid(hp_u)
    ops_m = HybridOps.from_hybrid(hp_m)
    data_u = device_data_hybrid(hp_u)
    data_m = device_data_hybrid(hp_m)
    rng = np.random.default_rng(21)
    x = jnp.asarray(rng.standard_normal((2, hp_u.pm.n_loc)))
    y_u = np.asarray(ops_u.matvec_local(data_u, x))
    y_m = np.asarray(ops_m.matvec_local(data_m, x))
    assert np.abs(y_m - y_u).max() / np.abs(y_u).max() < 1e-12
    d_u = np.asarray(ops_u.diag_local(data_u))
    d_m = np.asarray(ops_m.diag_local(data_m))
    assert np.abs(d_m - d_u).max() / np.abs(d_u).max() < 1e-12
    b_u = np.asarray(ops_u._node_block_local(data_u))
    b_m = np.asarray(ops_m._node_block_local(data_m))
    assert np.abs(b_m - b_u).max() / (np.abs(b_u).max() + 1e-30) < 1e-12


def test_combine_gather_matches_scatter(pair):
    """The scatter-free gather-combine (default) vs the row scatter —
    identical matvec and diag up to f64 summation-order noise."""
    import dataclasses

    _, (ops_h, data_h), pm_g, hp = pair
    assert ops_h.combine == "gather" and "combine" in data_h
    ops_s = dataclasses.replace(ops_h, combine="scatter")
    P = pm_g.n_parts
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(P, pm_g.n_loc)))
    yg = np.asarray(ops_h.matvec(data_h, x))
    ys = np.asarray(ops_s.matvec(data_h, x))
    np.testing.assert_allclose(yg, ys, rtol=0,
                               atol=1e-12 * np.abs(ys).max())
    dg = np.asarray(ops_h.diag(data_h))
    ds = np.asarray(ops_s.diag(data_h))
    np.testing.assert_allclose(dg, ds, rtol=0,
                               atol=1e-12 * np.abs(ds).max())
    # node-block preconditioner assembly and nodal averaging share the
    # combine; the scatter branches must stay live-equivalent too
    bg = np.asarray(ops_h._node_block_local(data_h))
    bs_ = np.asarray(ops_s._node_block_local(data_h))
    np.testing.assert_allclose(bg, bs_, rtol=0,
                               atol=1e-12 * np.abs(bs_).max())
    eg = ops_h.elem_strain(data_h, x)
    ag = np.asarray(ops_h.nodal_average(data_h, eg))
    as_ = np.asarray(ops_s.nodal_average(data_h, ops_s.elem_strain(data_h, x)))
    np.testing.assert_allclose(ag, as_, rtol=0,
                               atol=1e-11 * max(np.abs(as_).max(), 1e-30))


def test_combine_maps_cover_every_slot_once(pair):
    """Every real (non-pad-target) lattice slot appears in exactly one
    gidx/hgidx cell; pad cells all point at the zero row."""
    _, (ops_h, data_h), pm_g, hp = pair
    cm = hp.combine
    nn = hp.pm.n_node_loc
    for p in range(hp.pm.n_parts):
        tgt = np.concatenate(
            [lv.nidx[p].reshape(-1) for lv in hp.levels]).astype(np.int64)
        used = np.concatenate([cm.gidx[p].reshape(-1),
                               cm.hgidx[p].reshape(-1)])
        used = used[used < cm.n_slots]
        # exactly the slots whose target is a real node, each once
        expect = np.where(tgt < nn)[0]
        np.testing.assert_array_equal(np.sort(used), expect)
        # heavy node ids are real or pad
        assert (cm.hnode[p] <= nn).all()


def test_auto_backend_prefers_hybrid(model, monkeypatch):
    # ISSUE 14: hybrid auto-selection is deprecation-gated behind the
    # explicit opt-in (RUNBOOK "Scaling the setup path")...
    monkeypatch.setenv("PCG_TPU_ENABLE_HYBRID", "1")
    s = Solver(model, RunConfig(), mesh=make_mesh(4), n_parts=4)
    assert s.backend == "hybrid"


def test_auto_backend_hybrid_gate_defaults_general(model, monkeypatch):
    """...and WITHOUT the opt-in an octree model auto-routes to the
    general backend (explicit backend='hybrid' still honored)."""
    monkeypatch.delenv("PCG_TPU_ENABLE_HYBRID", raising=False)
    s = Solver(model, RunConfig(), mesh=make_mesh(4), n_parts=4)
    assert s.backend == "general"
    s2 = Solver(model, RunConfig(), mesh=make_mesh(4), n_parts=4,
                backend="hybrid")
    assert s2.backend == "hybrid"


def test_level_stencil_matches_pallas_kernel(pair):
    """The hybrid level stencil and the Pallas kernel share corner order:
    identical results on a real level grid (interpret mode)."""
    from pcg_mpi_solver_tpu.ops.pallas_matvec import structured_matvec_pallas

    _, (ops_h, data_h), _, hp = pair
    lv = data_h["levels"][-1]
    nb, bx, by, bz = ops_h.level_dims[-1]
    rng = np.random.default_rng(2)
    P = lv["ck"].shape[0]
    B = P * nb   # the stencil operates on the part*block batch
    xg = jnp.asarray(rng.normal(
        size=(B, 3, bx + 1, by + 1, bz + 1)), jnp.float32)
    Ke32 = data_h["brick_Ke"].astype(jnp.float32)
    ck32 = lv["ck"].astype(jnp.float32).reshape(B, bx, by, bz)
    y_xla = np.asarray(ops_h._stencil(Ke32, ck32, xg))
    y_pal = np.stack([
        np.asarray(structured_matvec_pallas(xg[b], ck32[b], Ke32,
                                            interpret=True))
        for b in range(B)])
    np.testing.assert_allclose(y_pal, y_xla, rtol=2e-5,
                               atol=2e-5 * max(np.abs(y_xla).max(), 1))


def test_general_f64_refresh_matches_stencil(model, monkeypatch):
    """PCG_TPU_HYBRID_F64_REFRESH=general swaps the out-of-loop f64
    matvecs onto a full general gather/scatter partition (compile-cost
    escape hatch for the octree flagship's 999 s stencil amul).  The
    operator must agree with the stencil form to f64 roundoff on the
    same partition, and a mixed solve must converge to the same answer."""
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=4000,
                            precision_mode="mixed"),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]))
    monkeypatch.setenv("PCG_TPU_HYBRID_F64_REFRESH", "stencil")
    s0 = Solver(model, cfg, mesh=make_mesh(4), n_parts=4, backend="hybrid")
    assert s0.f64_refresh == "stencil"
    r0 = s0.step(1.0)
    monkeypatch.setenv("PCG_TPU_HYBRID_F64_REFRESH", "general")
    s1 = Solver(model, cfg, mesh=make_mesh(4), n_parts=4, backend="hybrid")
    assert s1.f64_refresh == "general" and s1._refresh64 is not None

    # operator identity on a random f64 vector (padding is eff-masked)
    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.standard_normal((s1.pm.n_parts, s1.pm.n_loc)))
    y_sten = np.asarray(s0._amul64_fn(s0.data, v))
    y_gen = np.asarray(s1._amul64_fn(s1.data, v))
    np.testing.assert_allclose(
        y_gen, y_sten, rtol=1e-12,
        atol=1e-12 * max(1.0, np.abs(y_sten).max()))

    r1 = s1.step(1.0)
    assert r1.flag == 0 and r1.relres <= 1e-8
    assert r0.flag == 0
    u0 = np.asarray(s0.displacement_global())
    u1 = np.asarray(s1.displacement_global())
    np.testing.assert_allclose(u1, u0, rtol=1e-7,
                               atol=1e-9 * max(1.0, np.abs(u0).max()))

    # bucketed arm: types stacked into a few padded batched einsums
    # (compile-structure count ~8 instead of one per type)
    monkeypatch.setenv("PCG_TPU_HYBRID_F64_REFRESH", "bucketed")
    s2 = Solver(model, cfg, mesh=make_mesh(4), n_parts=4, backend="hybrid")
    assert s2.f64_refresh == "bucketed"
    y_bkt = np.asarray(s2._amul64_fn(s2.data, v))
    np.testing.assert_allclose(
        y_bkt, y_sten, rtol=1e-12,
        atol=1e-12 * max(1.0, np.abs(y_sten).max()))
    r2 = s2.step(1.0)
    assert r2.flag == 0 and r2.relres <= 1e-8
    u2 = np.asarray(s2.displacement_global())
    np.testing.assert_allclose(u2, u0, rtol=1e-7,
                               atol=1e-9 * max(1.0, np.abs(u0).max()))


def test_mixed_precision_hybrid(model):
    cfg = RunConfig(
        solver=SolverConfig(tol=1e-8, max_iter=4000, precision_mode="mixed"),
        time_history=TimeHistoryConfig(time_step_delta=[0.0, 1.0]))
    s = Solver(model, cfg, mesh=make_mesh(4), n_parts=4, backend="hybrid")
    res = s.step(1.0)
    assert res.flag == 0
    assert res.relres <= 1e-8


def test_tiled_blocks_match_dense(model):
    """Force block tiling (PCG_TPU_HYBRID_BLOCK=2 on a small model) and
    assert the tiled level grids produce the SAME matvec as the dense-
    bbox layout — block decomposition must not change the math, and
    block-boundary lattice nodes (shared by adjacent blocks) must
    accumulate exactly once per brick."""
    import os

    from pcg_mpi_solver_tpu.parallel.partition import make_elem_part

    ep = make_elem_part(model, 2, method="rcb")
    prev = os.environ.get("PCG_TPU_HYBRID_BLOCK")
    prev_m = os.environ.get("PCG_TPU_HYBRID_MERGE")
    try:
        # this test exercises the per-level dense-vs-tiled machinery; the
        # level merge (tested separately) would fold both into one batch
        os.environ["PCG_TPU_HYBRID_MERGE"] = "0"
        os.environ["PCG_TPU_HYBRID_BLOCK"] = "1000000"   # force dense
        hp_d = partition_hybrid(model, 2, elem_part=ep)
        os.environ["PCG_TPU_HYBRID_BLOCK"] = "2"         # force tiling
        hp_t = partition_hybrid(model, 2, elem_part=ep)
    finally:
        if prev is None:
            os.environ.pop("PCG_TPU_HYBRID_BLOCK", None)
        else:
            os.environ["PCG_TPU_HYBRID_BLOCK"] = prev
        if prev_m is None:
            os.environ.pop("PCG_TPU_HYBRID_MERGE", None)
        else:
            os.environ["PCG_TPU_HYBRID_MERGE"] = prev_m
    assert all(lv.nb == 1 for lv in hp_d.levels)
    assert any(lv.nb > 1 for lv in hp_t.levels), (
        "tiling did not engage — the tiled path is untested")
    ops_d = HybridOps.from_hybrid(hp_d)
    ops_t = HybridOps.from_hybrid(hp_t)
    data_d = device_data_hybrid(hp_d)
    data_t = device_data_hybrid(hp_t)
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.standard_normal((2, hp_d.pm.n_loc)))
    y_d = np.asarray(ops_d.matvec_local(data_d, x))
    y_t = np.asarray(ops_t.matvec_local(data_t, x))
    scale = np.abs(y_d).max()
    assert np.abs(y_t - y_d).max() / scale < 1e-12
    # diagonal and node-block assembly agree too
    d_d = np.asarray(ops_d.diag_local(data_d))
    d_t = np.asarray(ops_t.diag_local(data_t))
    assert np.abs(d_t - d_d).max() / np.abs(d_d).max() < 1e-12
    b_d = np.asarray(ops_d._node_block_local(data_d))
    b_t = np.asarray(ops_t._node_block_local(data_t))
    assert np.abs(b_t - b_d).max() / (np.abs(b_d).max() + 1e-30) < 1e-12
    # strain -> nodal averaging path agrees (exercises elem_strain,
    # elem_scale and nodal_average over tiled blocks)
    e_d = ops_d.elem_strain(data_d, x)
    e_t = ops_t.elem_strain(data_t, x)
    a_d = np.asarray(ops_d.nodal_average(data_d, e_d))
    a_t = np.asarray(ops_t.nodal_average(data_t, e_t))
    assert np.abs(a_t - a_d).max() / (np.abs(a_d).max() + 1e-30) < 1e-10


def test_hybrid_forms_match(pair):
    """Every stencil formulation (gse / gsplit / corner) must produce the
    same hybrid matvec — form is pinned per-ops at construction."""
    _, (ops_h, data_h), _, hp = pair
    rng = np.random.default_rng(11)
    P = data_h["eff"].shape[0]
    x = jnp.asarray(rng.standard_normal((P, ops_h.n_loc)))
    y_ref = np.asarray(ops_h.matvec(data_h, x))
    scale = np.abs(y_ref).max()
    for form in ("gsplit", "corner"):
        ops_f = HybridOps.from_hybrid(hp, form=form)
        y_f = np.asarray(ops_f.matvec(data_h, x))
        assert np.abs(y_f - y_ref).max() / scale < 1e-13, form
