"""Implicit Newmark-beta dynamics (solver/newmark.py) vs an independent
dense-matrix reference integrator, plus precision/preconditioner/backends.

The reference has no implicit integrator (its dynamics era was explicit-
only); this capability is BASELINE.json config 5."""

import numpy as np
import pytest

from pcg_mpi_solver_tpu.config import RunConfig, SolverConfig
from pcg_mpi_solver_tpu.models import make_cube_model
from pcg_mpi_solver_tpu.models.octree import make_octree_model
from pcg_mpi_solver_tpu.parallel.mesh import make_mesh
from pcg_mpi_solver_tpu.solver.newmark import NewmarkSolver


def dense_newmark(model, dt, deltas, beta=0.25, gamma=0.5, cm=0.0):
    """Independent numpy Newmark integrator on the dense assembled K."""
    K = np.asarray(model.assemble_csr().todense())
    M = model.diag_M.copy()
    n = model.n_dof
    fixed = np.zeros(n, bool)
    fixed[model.fixed_dof] = True
    free = ~fixed
    a0 = 1.0 / (beta * dt * dt)
    a1 = gamma / (beta * dt)
    a2 = 1.0 / (beta * dt)
    a3 = 1.0 / (2 * beta) - 1.0
    a4 = gamma / beta - 1.0
    a5 = dt * (gamma / (2 * beta) - 1.0)
    A = K + (a0 + a1 * cm) * np.diag(M)
    u = np.zeros(n)
    v = np.zeros(n)
    w = np.zeros(n)
    for d in deltas:
        rhs = model.F * d + M * (a0 * u + a2 * v + a3 * w) \
            + cm * M * (a1 * u + a4 * v + a5 * w)
        u2 = np.zeros(n)
        u2[fixed] = model.Ud[fixed] * d
        u2[free] = np.linalg.solve(A[np.ix_(free, free)],
                                   (rhs - A @ u2)[free])
        w2 = a0 * (u2 - u) - a2 * v - a3 * w
        v2 = v + dt * ((1 - gamma) * w + gamma * w2)
        v2[fixed] = model.Vd[fixed] * d
        u, v, w = u2, v2, w2
    return u, v, w


def _cfg(mode="direct", precond="jacobi", tol=1e-12):
    return RunConfig(solver=SolverConfig(tol=tol, max_iter=3000,
                                         precision_mode=mode,
                                         precond=precond))


DELTAS = [0.5, 1.0, 1.0, 0.7, 0.3]


def test_newmark_matches_dense_reference():
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, heterogeneous=True,
                            seed=0)
    dt = 0.2
    s = NewmarkSolver(model, _cfg(), mesh=make_mesh(4), n_parts=4, dt=dt,
                      damping=0.1)
    results = s.run(DELTAS)
    assert all(r.flag == 0 for r in results)
    u_ref, v_ref, w_ref = dense_newmark(model, dt, DELTAS, cm=0.1)
    u, v, w = s.state_global()
    scale = np.abs(u_ref).max()
    np.testing.assert_allclose(u, u_ref, atol=1e-8 * scale, rtol=1e-7)
    np.testing.assert_allclose(v, v_ref, atol=1e-7 * scale / dt, rtol=1e-6)
    np.testing.assert_allclose(w, w_ref, atol=1e-6 * scale / dt**2, rtol=1e-6)


def test_newmark_dirichlet_driven_matches_dense():
    model = make_cube_model(3, 3, 3, load="dirichlet", load_value=0.01)
    dt = 0.1
    s = NewmarkSolver(model, _cfg(), mesh=make_mesh(2), n_parts=2, dt=dt)
    for r in s.run(DELTAS):
        assert r.flag == 0
    u_ref, _, _ = dense_newmark(model, dt, DELTAS)
    u, _, _ = s.state_global()
    np.testing.assert_allclose(u, u_ref, atol=1e-8 * np.abs(u_ref).max(),
                               rtol=1e-7)


def test_newmark_static_limit():
    """dt -> inf: inertial terms vanish and one step is the static solve."""
    import scipy.sparse.linalg as spla

    model = make_cube_model(4, 3, 3, heterogeneous=True)
    s = NewmarkSolver(model, _cfg(), mesh=make_mesh(4), n_parts=4, dt=1e8)
    res = s.step(1.0)
    assert res.flag == 0
    K = model.assemble_csr().tocsc()
    free = np.setdiff1d(np.arange(model.n_dof), model.fixed_dof)
    u_stat = np.zeros(model.n_dof)
    u_stat[free] = spla.spsolve(K[np.ix_(free, free)], model.F[free])
    u = s.displacement_global()
    np.testing.assert_allclose(u, u_stat, rtol=1e-6,
                               atol=1e-9 * np.abs(u_stat).max())


def test_newmark_partition_count_parity():
    model = make_cube_model(4, 4, 4, heterogeneous=True)
    runs = {}
    for n_parts in (1, 8):
        s = NewmarkSolver(model, _cfg(), mesh=make_mesh(n_parts),
                          n_parts=n_parts, dt=0.2)
        s.run(DELTAS)
        runs[n_parts] = s.state_global()[0]
    np.testing.assert_allclose(runs[8], runs[1], rtol=1e-8,
                               atol=1e-11 * np.abs(runs[1]).max())


@pytest.mark.parametrize("mode,precond", [("mixed", "jacobi"),
                                          ("direct", "block3"),
                                          ("mixed", "block3")])
def test_newmark_modes(mode, precond):
    model = make_cube_model(4, 3, 3, heterogeneous=True)
    dt = 0.2
    tol = 1e-10 if mode == "mixed" else 1e-12
    s = NewmarkSolver(model, _cfg(mode, precond, tol), mesh=make_mesh(4),
                      n_parts=4, dt=dt)
    for r in s.run(DELTAS):
        assert r.flag == 0
    u_ref, _, _ = dense_newmark(model, dt, DELTAS)
    u, _, _ = s.state_global()
    np.testing.assert_allclose(u, u_ref, rtol=1e-5,
                               atol=1e-7 * np.abs(u_ref).max())


def test_newmark_hybrid_octree(monkeypatch):
    monkeypatch.setenv("PCG_TPU_ENABLE_HYBRID", "1")   # auto->hybrid gate
    model = make_octree_model(2, 2, 2, max_level=2, n_incl=2, seed=3,
                              load="traction", load_value=1.0)
    dt = 0.1
    s = NewmarkSolver(model, _cfg(), mesh=make_mesh(2), n_parts=2, dt=dt)
    assert s.backend == "hybrid"
    for r in s.run(DELTAS):
        assert r.flag == 0
    u_ref, _, _ = dense_newmark(model, dt, DELTAS)
    u, _, _ = s.state_global()
    np.testing.assert_allclose(u, u_ref, rtol=1e-6,
                               atol=1e-8 * np.abs(u_ref).max())


# Cube 4x3x3 (h=0.5, nu=0.3, heterogeneous seed 0), dt=0.2, damping=0.1,
# tol=1e-12, 5 steps of DELTAS, 4 parts on 4 devices.  Pinned at round 2.
GOLDEN_NEWMARK = {"iters": [19, 19, 19, 18, 18], "checksum": 158.3225146267945}


def test_newmark_golden():
    model = make_cube_model(4, 3, 3, h=0.5, nu=0.3, heterogeneous=True,
                            seed=0)
    s = NewmarkSolver(model, _cfg(tol=1e-12), mesh=make_mesh(4), n_parts=4,
                      dt=0.2, damping=0.1)
    res = s.run(DELTAS)
    assert all(r.flag == 0 for r in res)
    iters = [r.iters for r in res]
    assert all(abs(a - b) <= 1 for a, b in zip(iters, GOLDEN_NEWMARK["iters"])), iters
    checksum = float(np.abs(s.state_global()[0]).sum())
    assert np.isclose(checksum, GOLDEN_NEWMARK["checksum"], rtol=1e-8), checksum


@pytest.mark.parametrize("mode", ["direct", "mixed"])
def test_newmark_chunked_matches_one_shot(mode):
    """iters_per_dispatch splits each step's PCG into capped dispatches
    (solver/chunked.py); trajectories must match the one-shot path."""
    model = make_cube_model(4, 3, 3, heterogeneous=True)
    dt = 0.2
    tol = 1e-10 if mode == "mixed" else 1e-12

    def solve(ipd):
        cfg = RunConfig(solver=SolverConfig(
            tol=tol, max_iter=3000, precision_mode=mode,
            iters_per_dispatch=ipd))
        s = NewmarkSolver(model, cfg, mesh=make_mesh(4), n_parts=4, dt=dt)
        res = s.run(DELTAS)
        assert all(r.flag == 0 for r in res)
        return s.state_global()[0], [r.iters for r in res]

    u1, it1 = solve(0)
    u2, it2 = solve(7)
    if mode == "direct":
        # resumable carry: iteration-for-iteration identical
        assert it1 == it2, (it1, it2)
        np.testing.assert_allclose(u2, u1, rtol=1e-12, atol=0)
    else:
        scale = np.abs(u1).max()
        assert np.abs(u2 - u1).max() / scale < 1e-7


def test_newmark_unconditional_stability():
    """Average-acceleration Newmark at 50x the explicit CFL dt: bounded
    response (the explicit integrator diverges immediately at this dt)."""
    from pcg_mpi_solver_tpu.solver.dynamics import stable_dt

    model = make_cube_model(3, 3, 3)
    dt = 50.0 * stable_dt(model)
    s = NewmarkSolver(model, _cfg(tol=1e-10), mesh=make_mesh(2), n_parts=2,
                      dt=dt)
    results = s.run([1.0] * 20)
    assert all(r.flag == 0 for r in results)
    u, v, w = s.state_global()
    # static displacement scale for this load
    assert np.abs(u).max() < 1e3 * (np.abs(model.F).max() / model.ck.min())
    assert np.isfinite(v).all() and np.isfinite(w).all()


def test_newmark_gamma_validation():
    """gamma <= 0 is rejected; gamma < 1/2 (negative algorithmic damping,
    unbounded growth at flag=0 per step) warns loudly (ADVICE r2)."""
    model = make_cube_model(2, 2, 2)
    with pytest.raises(ValueError, match="gamma"):
        NewmarkSolver(model, _cfg(), mesh=make_mesh(1), n_parts=1, gamma=0.0)
    with pytest.warns(UserWarning, match="unstable"):
        NewmarkSolver(model, _cfg(), mesh=make_mesh(1), n_parts=1, gamma=0.4)


def test_mass_shifted_ops_blocks_partial_assembly():
    """The K+a0*M wrapper must refuse every *_local partial-assembly entry
    point: delegating them silently would return K-only values without the
    mass shift (ADVICE r2)."""
    from pcg_mpi_solver_tpu.solver.newmark import MassShiftedOps

    model = make_cube_model(2, 2, 2)
    s = NewmarkSolver(model, _cfg(), mesh=make_mesh(1), n_parts=1)
    # test the solver's OWN wrapped ops, not a fresh wrapper
    w = s.ops
    assert isinstance(w, MassShiftedOps)
    for name in ("matvec_local", "diag_local", "_node_block_local"):
        with pytest.raises(NotImplementedError):
            getattr(w, name)(s.data) if name != "matvec_local" \
                else w.matvec_local(s.data, None)
    # shift-invariant members still delegate to the unshifted base
    assert w.wdot == w.base.wdot
