"""Matvec microbenchmark: XLA vs Pallas v1 (per-plane VPU), v2 (per-plane
MXU), and v3 (chunked double-buffered MXU, swept over chunk sizes).

Times the structured-slab matvec formulations in isolation on the current
default device.  Usage: python examples/bench_matvec.py [nx [ny [nz]]]
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.bench import cached_model
from pcg_mpi_solver_tpu.ops.pallas_matvec import (
    structured_matvec_pallas, structured_matvec_pallas_v2,
    structured_matvec_pallas_v3, structured_matvec_pallas_v4)
from pcg_mpi_solver_tpu.parallel.structured import (
    StructuredOps, device_data_structured, partition_structured)


from pcg_mpi_solver_tpu.utils.backend_probe import probe_or_exit  # noqa: E402

probe_or_exit()


def timeit(fn, *args, n=20):
    y = fn(*args)
    jax.block_until_ready(y)
    float(jnp.asarray(y).ravel()[0])     # tunneled-device sync
    t0 = time.perf_counter()
    for _ in range(n):
        y = fn(*args)
    float(jnp.asarray(y).ravel()[0])
    return (time.perf_counter() - t0) / n, y


def main():
    nx = int(sys.argv[1]) if len(sys.argv) > 1 else 150
    ny = int(sys.argv[2]) if len(sys.argv) > 2 else nx
    nz = int(sys.argv[3]) if len(sys.argv) > 3 else nx
    model = cached_model("cube", nx=nx, ny=ny, nz=nz,
                         heterogeneous=True)
    sp = partition_structured(model, 1)
    data = device_data_structured(sp, jnp.float32)
    ops = StructuredOps.from_partition(sp, dot_dtype=jnp.float32)
    blk = data["blocks"][0]
    print(f"{model.n_dof} dofs on {jax.devices()[0]}", flush=True)

    rng = np.random.default_rng(0)
    x = jax.device_put(
        jnp.asarray(rng.normal(size=(1, sp.n_loc)), jnp.float32))
    xg = x.reshape(1, 3, nx + 1, ny + 1, nz + 1)[0]

    # the form is pinned per-ops instance, so the A/B is explicit — an
    # inherited PCG_TPU_MATVEC_FORM cannot make this compare a form
    # against itself
    import dataclasses

    ops_gse = dataclasses.replace(ops, form="gse")
    xla = jax.jit(lambda d, xx: ops_gse.matvec_local(d, xx))
    t_xla, y0 = timeit(xla, data, x)
    print(f"xla (gse):    {t_xla*1e3:8.3f} ms/matvec", flush=True)

    # alternative XLA formulations: gsplit (gse minus the gather concat —
    # one fewer (24, cells) HBM round-trip) and corner (no (24, cells)
    # intermediates at all; scalar-FMA-bound, 0.57x on v5e in wave 2/3)
    for form in ("gsplit", "corner"):
        ops_f = dataclasses.replace(ops, form=form)
        fn = jax.jit(lambda d, xx, o=ops_f: o.matvec_local(d, xx))
        try:
            t_c, y_c = timeit(fn, data, x)
            err = float(jnp.abs(y_c - y0).max() / jnp.abs(y0).max())
            print(f"xla ({form}): {t_c*1e3:8.3f} ms/matvec  "
                  f"(vs gse {t_xla/t_c:5.2f}x, maxrelerr {err:.2e})",
                  flush=True)
        except Exception as e:                      # noqa: BLE001
            print(f"xla ({form}): FAILED {type(e).__name__}: {e}",
                  flush=True)

    variants = [("pallas v1", structured_matvec_pallas),
                ("pallas v2", structured_matvec_pallas_v2)]
    for c in (8, 16):
        variants.append((f"pallas v3 C={c}", functools.partial(
            structured_matvec_pallas_v3, planes=c)))
    # C=16 is expected to exceed the ~16 MB VMEM budget at flagship m —
    # included because its failure mode (fast alloc error) is cheap and
    # pins the ceiling; C=24 would only repeat it
    for c in (8, 16):
        variants.append((f"pallas v4 C={c}", functools.partial(
            structured_matvec_pallas_v4, planes=c)))
    from pcg_mpi_solver_tpu.ops.pallas_matvec import (
        structured_matvec_pallas_v5, structured_matvec_pallas_v6,
        structured_matvec_pallas_v7, structured_matvec_pallas_v8,
        structured_matvec_pallas_v9)
    for c in (8, 16):
        variants.append((f"pallas v5 C={c}", functools.partial(
            structured_matvec_pallas_v5, planes=c)))
    # v6 at C=16 exceeds the ~16 MB VMEM budget at flagship m (slab
    # buffers are (2,3,C+8,mt128)); only C=8 is expected to fit
    variants.append(("pallas v6 C=8", functools.partial(
        structured_matvec_pallas_v6, planes=8)))
    variants.append(("pallas v7 C=8", functools.partial(
        structured_matvec_pallas_v7, planes=8)))
    variants.append(("pallas v8 C=8", functools.partial(
        structured_matvec_pallas_v8, planes=8)))
    variants.append(("pallas v9 C=8", functools.partial(
        structured_matvec_pallas_v9, planes=8)))
    # BENCH_MATVEC_VARIANTS="v6,v8" runs only those Pallas variants: on
    # hardware every known-failing variant burns a failed REMOTE compile
    # that can wedge the device grant for minutes (docs/RUNBOOK.md) —
    # v1-v5/v7 are chipless-pinned failures at flagship scale, so
    # sessions should skip straight to the candidates.
    import os

    only = [v.strip() for v in
            os.environ.get("BENCH_MATVEC_VARIANTS", "").split(",")
            if v.strip()]
    if only:
        variants = [(n, f) for n, f in variants
                    if any(f"pallas {v} " in n + " " or n.endswith(v)
                           for v in only)]
    for name, fn in variants:
        try:
            t, y = timeit(fn, xg, blk["ck"][0], blk["Ke"])
            err = float(jnp.abs(y.reshape(-1) - y0[0]).max()
                        / jnp.abs(y0).max())
            print(f"{name}: {t*1e3:8.3f} ms/matvec  "
                  f"(vs xla {t_xla/t:5.2f}x, maxrelerr {err:.2e})",
                  flush=True)
        except Exception as e:                      # noqa: BLE001
            print(f"{name}: FAILED {type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
