"""Per-iteration cost breakdown of the structured-backend PCG on the
current accelerator: isolates the matvec, the f64-accumulated weighted
dots, and a full synthetic iteration body, so regressions or wins can be
attributed (RUNBOOK "performance triage order" step 1.5 — between the
matvec microbench and the end-to-end bench).

Usage: python examples/bench_iter_breakdown.py [n]      (default 150)
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.bench import cached_model
from pcg_mpi_solver_tpu.parallel.structured import (
    StructuredOps, device_data_structured, partition_structured)


from pcg_mpi_solver_tpu.utils.backend_probe import probe_or_exit  # noqa: E402

probe_or_exit()


def _sync(y):
    """Force a value transfer: on tunneled devices block_until_ready can
    ack before execution finishes (same caveat examples/bench_matvec.py
    works around with its inline float() reads)."""
    leaf = jax.tree.leaves(y)[0]
    float(jnp.asarray(leaf).ravel()[0])


def timeit(f, *args, reps=10):
    y = f(*args)
    _sync(y)
    t0 = time.perf_counter()
    for _ in range(reps):
        y = f(*args)
    _sync(y)
    return (time.perf_counter() - t0) / reps * 1e3


def main():
    # f64-accumulated dots are the thing being measured — enable x64
    jax.config.update("jax_enable_x64", True)
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 150
    t0 = time.perf_counter()
    model = cached_model("cube", nx=n, ny=n, nz=n, E=30e9, nu=0.2,
                         load="traction", load_value=1e6)
    print(f"# model {model.n_dof} dofs (gen {time.perf_counter()-t0:.1f}s)",
          flush=True)
    sp = partition_structured(model, 1)
    d32 = device_data_structured(sp, jnp.float32)
    ops32 = StructuredOps.from_partition(sp, dot_dtype=jnp.float32)
    ops64 = StructuredOps.from_partition(sp, dot_dtype=jnp.float64)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(1, sp.n_loc)),
                    jnp.float32)
    w = d32["weight"] * d32["eff"]

    mv = jax.jit(lambda d, x: ops32.matvec(d, x))
    print(f"matvec f32:        {timeit(mv, d32, x):8.3f} ms", flush=True)
    for name, ops in (("f32", ops32), ("f64", ops64)):
        dot = jax.jit(lambda w, a, b, o=ops: o.wdot(w, a, b))
        print(f"wdot {name} acc:      {timeit(dot, w, x, x):8.3f} ms",
              flush=True)
        dots3 = jax.jit(lambda w, a, b, o=ops: o.wdots(w, [(a, a), (b, b),
                                                          (a, b)]))
        print(f"fused 3-dot {name}:   {timeit(dots3, w, x, x):8.3f} ms",
              flush=True)

    def make_body(ops):
        def iter_body(d, x):
            eff = d["eff"]
            w = d["weight"] * eff
            q = eff * ops.matvec(d, x)
            rho = ops.wdot(w, x, q)
            pq = ops.wdot(w, q, q)
            s3 = ops.wdots(w, [(x, x), (q, q), (x, q)])
            ax = x + 0.5 * q
            z = eff * (q - 0.3 * x)
            return ax + z, rho + pq + s3.sum()

        return jax.jit(iter_body)

    for name, ops in (("f64", ops64), ("f32", ops32)):
        print(f"iter body ({name} dots): {timeit(make_body(ops), d32, x):8.3f} ms",
              flush=True)


if __name__ == "__main__":
    main()
