"""End-to-end demo: the reference's 5-stage pipeline on a synthetic model.

Mirrors examples/run_basic_script.bash of the reference (ingest -> metis ->
partition -> settings -> solve -> export, reference: run_basic_script.bash:
19-55) using this framework's stages.  Run:

    python examples/run_demo.py [--nx 24] [--scratch ./scratch_demo]

Stages:
  1. build + write the model in MDF format (stands in for concrete.zip ingest)
  2. partition (native graph partitioner when available)
  3. quasi-static solve (mixed precision) with checkpoints + probe plots
  4. principal-stress/strain contour export per key frame
  5. VTK (.vtu) export for ParaView
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nx", type=int, default=24)
    ap.add_argument("--scratch", default="./scratch_demo")
    ap.add_argument("--tol", type=float, default=1e-7)
    args = ap.parse_args()

    import jax
    import numpy as np

    from pcg_mpi_solver_tpu import RunConfig, SolverConfig, TimeHistoryConfig
    from pcg_mpi_solver_tpu.models import make_cube_model
    from pcg_mpi_solver_tpu.models.mdf import read_mdf, write_mdf
    from pcg_mpi_solver_tpu.parallel import make_mesh
    from pcg_mpi_solver_tpu.parallel.partition import make_elem_part
    from pcg_mpi_solver_tpu.solver import Solver
    from pcg_mpi_solver_tpu.utils.io import RunStore
    from pcg_mpi_solver_tpu.vtk.export import export_vtk

    # -- 1. ingest ------------------------------------------------------
    t0 = time.perf_counter()
    model = make_cube_model(args.nx, args.nx * 2 // 3, args.nx * 2 // 3,
                            E=30e9, nu=0.2, load="traction", load_value=1e6,
                            heterogeneous=True)
    mdf_dir = os.path.join(args.scratch, "ModelData", "MDF")
    write_mdf(model, mdf_dir)
    model = read_mdf(mdf_dir)     # round-trip through the on-disk format
    print(f">ingest: {model.n_elem} elems / {model.n_node} nodes / "
          f"{model.n_dof} dofs  ({time.perf_counter()-t0:.2f}s)")

    # -- 2. partition ---------------------------------------------------
    t0 = time.perf_counter()
    n_dev = len(jax.devices())
    n_parts = max(n_dev, 2)
    part = make_elem_part(model, n_parts, method="auto")
    print(f">partition: {n_parts} parts, sizes {np.bincount(part)} "
          f"({time.perf_counter()-t0:.2f}s)")

    # -- 3. solve -------------------------------------------------------
    cfg = RunConfig(
        scratch_path=args.scratch,
        model_name="demo",
        checkpoint_every=1,
        solver=SolverConfig(tol=args.tol, max_iter=10000,
                            precision_mode="mixed", dtype="float32"),
        time_history=TimeHistoryConfig(
            time_step_delta=[0.0, 0.5, 1.0],
            export_vars="U D ES PS PE",
            plot_flag=True,
            probe_dofs=(3 * (model.n_node - 1), 3 * (model.n_node - 1) + 2),
        ),
    )
    n_dev_used = n_dev if n_parts % n_dev == 0 else 1
    s = Solver(model, cfg, mesh=make_mesh(n_dev_used), n_parts=n_parts,
               elem_part=part)
    store = RunStore(cfg.result_path, cfg.model_name)
    res = s.solve(store=store)
    for t, r in enumerate(res, 1):
        print(f">step {t}: flag={r.flag} iters={r.iters} "
              f"relres={r.relres:.3e} wall={r.wall_s:.2f}s [{s.backend}]")
    td = s.time_data()
    print(f">calc {td['Mean_CalcTime']:.2f}s  compile~{td['Compile_Time_Est']:.2f}s "
          f"export {td['Export_Time']:.2f}s")

    # -- 4/5. export ----------------------------------------------------
    t0 = time.perf_counter()
    files = export_vtk(model, store, ["U", "PS1", "PS3", "ES"], "Full")
    print(f">vtk: {len(files)} files -> {store.vtk_path} "
          f"({time.perf_counter()-t0:.2f}s)")
    print(">success!")


if __name__ == "__main__":
    main()
