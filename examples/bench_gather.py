"""Hybrid level gather/scatter microbenchmark: the row-traffic side.

The hybrid octree matvec moves data between the flat local dof rows and
the per-level block lattices twice per level per matvec:

    gather:  jnp.take of (rows, 3) from the padded node-row table
    scatter: vmap'd  y.at[idx].add(rows)  back into the dof vector

TPU lowers arbitrary indexed reads/writes far less efficiently than
dense math (parallel/structured.py measured per-ELEMENT gathers at
~28 ms for 1.2M rows at 160k dofs).  Whether the hybrid's per-NODE
row traffic is the octree flagship's bottleneck decides the next
optimization (level-owned contiguous node ordering vs stencil work) —
this isolates exactly that cost at flagship-like sizes.

Usage: python examples/bench_gather.py [n_nodes_millions [n_rows_millions]]
(defaults 1.9M nodes / 7.4M gathered rows — the 5.67M-dof octree's
finest-level numbers at PCG_TPU_HYBRID_BLOCK=8)
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _sync(y):
    float(jnp.asarray(jax.tree.leaves(y)[0]).ravel()[0])


def timeit(f, *args, reps=10):
    y = f(*args)
    _sync(y)
    t0 = time.perf_counter()
    for _ in range(reps):
        y = f(*args)
    _sync(y)
    return (time.perf_counter() - t0) / reps * 1e3


def main():
    n_nodes = int(float(sys.argv[1]) * 1e6) if len(sys.argv) > 1 else 1_900_000
    n_rows = int(float(sys.argv[2]) * 1e6) if len(sys.argv) > 2 else 7_400_000
    rng = np.random.default_rng(0)
    # ~70% of lattice points resolve to real nodes, the rest to the pad
    # row (holes/non-local) — matches the blocked flagship's fill
    idx = rng.integers(0, n_nodes, size=n_rows).astype(np.int32)
    idx[rng.random(n_rows) < 0.3] = n_nodes
    x3p = jnp.asarray(rng.standard_normal((n_nodes + 1, 3)), jnp.float32)
    idxd = jnp.asarray(idx)
    rows = jnp.asarray(rng.standard_normal((n_rows, 3)), jnp.float32)
    y0 = jnp.zeros((n_nodes, 3), jnp.float32)
    print(f"{n_nodes/1e6:.2f}M nodes, {n_rows/1e6:.2f}M rows on "
          f"{jax.devices()[0]}", flush=True)

    gather = jax.jit(lambda t, i: jnp.take(t, i, axis=0, mode="clip"))
    t = timeit(gather, x3p, idxd)
    print(f"row gather:  {t:8.3f} ms  ({t*1e6/n_rows:6.1f} ns/row, "
          f"{n_rows*12/t/1e6:7.1f} GB/s effective)", flush=True)

    scatter = jax.jit(lambda y, i, r: y.at[i].add(r, mode="drop"))
    t = timeit(scatter, y0, idxd, rows)
    print(f"row scatter: {t:8.3f} ms  ({t*1e6/n_rows:6.1f} ns/row)",
          flush=True)

    # reference point: a dense copy of the same byte volume
    big = jnp.asarray(rng.standard_normal((n_rows, 3)), jnp.float32)
    t = timeit(jax.jit(lambda a: a * 1.0000001), big)
    print(f"dense same-bytes pass: {t:8.3f} ms", flush=True)


if __name__ == "__main__":
    main()
