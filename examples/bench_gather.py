"""Hybrid level gather/scatter microbenchmark: the row-traffic side.

The hybrid octree matvec moves data between the flat local dof rows and
the per-level block lattices twice per level per matvec:

    gather:  jnp.take of (rows, 3) from the padded node-row table
    scatter: vmap'd  y.at[idx].add(rows)  back into the dof vector

TPU lowers arbitrary indexed reads/writes far less efficiently than
dense math (parallel/structured.py measured per-ELEMENT gathers at
~28 ms for 1.2M rows at 160k dofs).  Whether the hybrid's per-NODE
row traffic is the octree flagship's bottleneck decides the next
optimization (level-owned contiguous node ordering vs stencil work) —
this isolates exactly that cost at flagship-like sizes.

Usage: python examples/bench_gather.py [n_nodes_millions [n_rows_millions]]
(defaults 1.9M nodes / 7.4M gathered rows — the 5.67M-dof octree's
finest-level numbers at PCG_TPU_HYBRID_BLOCK=8)
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


from pcg_mpi_solver_tpu.utils.backend_probe import probe_or_exit  # noqa: E402

probe_or_exit()


def _sync(y):
    float(jnp.asarray(jax.tree.leaves(y)[0]).ravel()[0])


def timeit(f, *args, reps=10):
    y = f(*args)
    _sync(y)
    t0 = time.perf_counter()
    for _ in range(reps):
        y = f(*args)
    _sync(y)
    return (time.perf_counter() - t0) / reps * 1e3


def main():
    jax.config.update("jax_enable_x64", True)   # the cumsum-diff variant
    n_nodes = int(float(sys.argv[1]) * 1e6) if len(sys.argv) > 1 else 1_900_000
    n_rows = int(float(sys.argv[2]) * 1e6) if len(sys.argv) > 2 else 7_400_000
    rng = np.random.default_rng(0)
    # ~70% of lattice points resolve to real nodes, the rest to the pad
    # row (holes/non-local) — matches the blocked flagship's fill
    idx = rng.integers(0, n_nodes, size=n_rows).astype(np.int32)
    idx[rng.random(n_rows) < 0.3] = n_nodes
    x3p = jnp.asarray(rng.standard_normal((n_nodes + 1, 3)), jnp.float32)
    idxd = jnp.asarray(idx)
    rows = jnp.asarray(rng.standard_normal((n_rows, 3)), jnp.float32)
    y0 = jnp.zeros((n_nodes, 3), jnp.float32)
    print(f"{n_nodes/1e6:.2f}M nodes, {n_rows/1e6:.2f}M rows on "
          f"{jax.devices()[0]}", flush=True)

    gather = jax.jit(lambda t, i: jnp.take(t, i, axis=0, mode="clip"))
    t = timeit(gather, x3p, idxd)
    print(f"row gather:  {t:8.3f} ms  ({t*1e6/n_rows:6.1f} ns/row, "
          f"{n_rows*12/t/1e6:7.1f} GB/s effective)", flush=True)

    scatter = jax.jit(lambda y, i, r: y.at[i].add(r, mode="drop"))
    t = timeit(scatter, y0, idxd, rows)
    print(f"row scatter: {t:8.3f} ms  ({t*1e6/n_rows:6.1f} ns/row)",
          flush=True)

    # --- combine-step alternatives (2026-07-30 session measured the
    # duplicate scatter at 88.7 ns/row vs 5.9 gather — these decide the
    # hybrid backend's scatter-free redesign) -------------------------

    # (a) scatter with SORTED indices (host-side pre-sort is free at
    # partition time; rows arrive pre-permuted)
    idx_sorted = jnp.asarray(np.sort(idx))
    t = timeit(jax.jit(lambda y, i, r: y.at[i].add(
        r, mode="drop", indices_are_sorted=True)), y0, idx_sorted, rows)
    print(f"row scatter sorted:        {t:8.3f} ms  ({t*1e6/n_rows:6.1f} "
          "ns/row)", flush=True)

    # (b) UNIQUE+sorted scatter (one slot per node — what a block-face
    # fold pass would leave behind)
    n_uniq = min(n_nodes, n_rows)
    uidx = jnp.asarray(np.arange(n_uniq, dtype=np.int32))
    urows = rows[:n_uniq]
    t = timeit(jax.jit(lambda y, i, r: y.at[i].add(
        r, mode="drop", indices_are_sorted=True, unique_indices=True)),
        y0, uidx, urows)
    print(f"row scatter unique+sorted: {t:8.3f} ms  ({t*1e6/n_uniq:6.1f} "
          "ns/row, {:.2f}M rows)".format(n_uniq / 1e6), flush=True)

    # (c) gather-transpose combine: rows pre-sorted by target node; each
    # node sums a run of <= K slots via K masked gathers (start/len built
    # at partition time).  Modeled here with the measured fill's run
    # structure from the random idx.
    order = np.argsort(idx, kind="stable")
    sidx = idx[order]
    starts = np.searchsorted(sidx, np.arange(n_nodes, dtype=np.int64))
    lens = np.diff(np.append(starts, len(sidx)))
    K = 2
    gidx = np.minimum(starts[:, None] + np.arange(K)[None],
                      len(sidx) - 1).astype(np.int32)
    gmask = (np.arange(K)[None] < np.minimum(lens, K)[:, None])
    gidx_d, gmask_d = jnp.asarray(gidx), jnp.asarray(gmask[..., None],
                                                     jnp.float32)
    rows_sorted = jnp.asarray(np.asarray(rows)[order])

    def combine_k(rs, gi, gm):
        acc = None
        for k in range(K):
            t_ = jnp.take(rs, gi[:, k], axis=0) * gm[:, k]
            acc = t_ if acc is None else acc + t_
        return acc
    t = timeit(jax.jit(combine_k), rows_sorted, gidx_d, gmask_d)
    cov = float((lens <= K).mean())
    print(f"gather-combine K={K}:        {t:8.3f} ms  (covers {cov*100:.0f}% "
          "of nodes; + residual scatter for the rest)", flush=True)

    # (d) cumsum-difference segmented sum (exact run lengths, any K):
    # f64 prefix over sorted rows + two boundary gathers
    ends = jnp.asarray((starts + lens - 1).astype(np.int32))
    starts_d = jnp.asarray(starts.astype(np.int32))
    has = jnp.asarray((lens > 0)[:, None].astype(np.float32))

    def cumsum_diff(rs, e, s0, h):
        cs = jnp.cumsum(rs.astype(jnp.float64), axis=0)
        hi = jnp.take(cs, e, axis=0)
        lo = jnp.where((s0 == 0)[:, None], 0.0,
                       jnp.take(cs, jnp.maximum(s0 - 1, 0), axis=0))
        return ((hi - lo) * h).astype(jnp.float32)
    t = timeit(jax.jit(cumsum_diff), rows_sorted, ends, starts_d, has)
    print(f"cumsum-diff combine:       {t:8.3f} ms  (exact, f64 prefix)",
          flush=True)

    # reference point: a dense copy of the same byte volume
    big = jnp.asarray(rng.standard_normal((n_rows, 3)), jnp.float32)
    t = timeit(jax.jit(lambda a: a * 1.0000001), big)
    print(f"dense same-bytes pass: {t:8.3f} ms", flush=True)


if __name__ == "__main__":
    main()
