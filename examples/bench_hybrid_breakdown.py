"""Per-matvec cost breakdown of the HYBRID (octree) backend: isolates
the per-level row gathers, the block stencils, and the row scatters that
make up matvec_local, so the octree flagship's bottleneck is attributable
on real hardware (RUNBOOK on-hardware checklist, octree leg).

Usage: python examples/bench_hybrid_breakdown.py [n0 [level [n_incl]]]
(default 22 4 6 — the 5.67M-dof flagship; use 10 3 6 for a quick run)
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from pcg_mpi_solver_tpu.bench import cached_model
from pcg_mpi_solver_tpu.parallel.hybrid import (
    HybridOps, device_data_hybrid, partition_hybrid)

from pcg_mpi_solver_tpu.utils.backend_probe import probe_or_exit  # noqa: E402

probe_or_exit()



def _sync(y):
    float(jnp.asarray(jax.tree.leaves(y)[0]).ravel()[0])


def timeit(f, *args, reps=10):
    y = f(*args)
    _sync(y)
    t0 = time.perf_counter()
    for _ in range(reps):
        y = f(*args)
    _sync(y)
    return (time.perf_counter() - t0) / reps * 1e3


def main():
    n0 = int(sys.argv[1]) if len(sys.argv) > 1 else 22
    level = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    incl = int(sys.argv[3]) if len(sys.argv) > 3 else 6
    t0 = time.perf_counter()
    model = cached_model("octree", nx0=n0, ny0=n0, nz0=n0, max_level=level,
                         n_incl=incl, seed=2, E=30e9, nu=0.2,
                         load="traction", load_value=1e6)
    print(f"# model {model.n_dof} dofs / {model.n_elem} elems "
          f"(gen {time.perf_counter()-t0:.1f}s)", flush=True)
    t0 = time.perf_counter()
    hp = partition_hybrid(model, 1)
    ops = HybridOps.from_hybrid(hp, dot_dtype=jnp.float32)
    data = device_data_hybrid(hp, jnp.float32)
    print(f"# partition {time.perf_counter()-t0:.1f}s; levels: "
          + ", ".join(f"s={lv.size} nb={lv.nb} {lv.bx}x{lv.by}x{lv.bz}"
                      for lv in hp.levels), flush=True)

    rng = np.random.default_rng(0)
    x = jax.device_put(
        jnp.asarray(rng.normal(size=(1, hp.pm.n_loc)), jnp.float32))

    t_mv = timeit(jax.jit(lambda d, xx: ops.matvec_local(d, xx)), data, x)
    print(f"matvec_local (all):    {t_mv:8.3f} ms", flush=True)

    # per-level pieces (jitted separately — sums can exceed the fused
    # whole; the split still attributes the dominant cost)
    for i, dims in enumerate(ops.level_dims):
        lv = data["levels"][i]

        def g_fn(d, xx, i=i, dims=dims):
            return ops._level_gather(ops._rows_pad(xx), d["levels"][i],
                                     dims, 1)

        jg = jax.jit(g_fn)
        t_g = timeit(jg, data, x)
        xg = jg(data, x)

        def s_fn(d, xg_, i=i, dims=dims):
            ck = d["levels"][i]["ck"]
            ck = ck.reshape((dims[0],) + ck.shape[2:])
            return ops._stencil(d["brick_Ke"], ck, xg_)

        js = jax.jit(s_fn)
        t_s = timeit(js, data, xg)
        yg = js(data, xg)
        del xg     # free this level's lattice batch before the next

        def sc_fn(d, yg_, i=i, dims=dims):
            y0 = jnp.zeros((1, ops.n_loc), yg_.dtype)
            return ops._level_scatter_add(y0, yg_, d["levels"][i], dims, 1)

        t_sc = timeit(jax.jit(sc_fn), data, yg)
        del yg
        nrows = int(np.prod(lv["nidx"].shape))
        print(f"level {i} (nb={dims[0]} {dims[1]}x{dims[2]}x{dims[3]}, "
              f"{nrows/1e6:.2f}M rows): gather {t_g:7.3f}  stencil "
              f"{t_s:7.3f}  scatter {t_sc:7.3f} ms", flush=True)


if __name__ == "__main__":
    main()
