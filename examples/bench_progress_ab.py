"""A/B the mixed-mode progress-rate inner exit
(SolverConfig.mixed_progress_window) at a given cube size: window 150
(the round-4 design value) vs 0 (off — the default since the negative
96^3 measurement, docs/BENCH_LOG.md 2026-08-01).

The knob's design target was the f32 inner-cycle grind at the
10.33M-dof flagship (docs/BENCH_LOG.md: ~670 iterations of sub-linear
residual progress before the cycle tolerance); VERDICT r04 weak #3
flagged that the default went ON with zero measurements at any scale
where the exit fires.  This script measured exactly that: at 64^3 the
exit never fires (bit-identical); at 96^3 it fires and COSTS +24%
total iterations — hence the default flip.  Kept for the true-flagship
hardware A/B.

Usage: python examples/bench_progress_ab.py [nx] [--window W]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_one(model, window):
    import jax

    from pcg_mpi_solver_tpu import RunConfig, SolverConfig
    from pcg_mpi_solver_tpu.parallel import make_mesh
    from pcg_mpi_solver_tpu.solver import Solver

    cfg = RunConfig(solver=SolverConfig(
        tol=1e-7, max_iter=20000, precision_mode="mixed",
        mixed_progress_window=window))
    s = Solver(model, cfg, mesh=make_mesh(1), n_parts=1)
    r0 = s.step(1.0)                    # warm (compile)
    s.reset_state()
    t0 = time.perf_counter()
    r = s.step(1.0)
    wall = time.perf_counter() - t0
    del s
    return dict(flag=int(r.flag), iters=int(r.iters),
                relres=float(r.relres), wall_s=round(wall, 2),
                warm_iters=int(r0.iters))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("nx", nargs="?", type=int, default=64)
    ap.add_argument("--window", type=int, default=150,
                    help="ON-arm window (default 150, the round-4 design "
                         "value — NOT the SolverConfig default, which is "
                         "0/off since the negative 96^3 A/B)")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the real accelerator (default: pin CPU — "
                         "the axon sitecustomize otherwise hangs a fresh "
                         "process on a wedged tunnel, docs/RUNBOOK.md)")
    args = ap.parse_args()

    import jax

    if not args.tpu:
        # iteration STRUCTURE (counts/cycles) is platform-independent;
        # the pin must land before the first device touch
        jax.config.update("jax_platforms", "cpu")
    print("# running on", jax.devices()[0].platform, flush=True)

    from pcg_mpi_solver_tpu.bench import cached_model

    n = args.nx
    model = cached_model("cube", nx=n, ny=n, nz=n, E=30e9, nu=0.2,
                         load="traction", load_value=1e6,
                         heterogeneous=True)
    print(f"# model {model.n_dof} dofs ({n}^3)", flush=True)
    for label, window in (("progress_on", args.window), ("progress_off", 0)):
        res = run_one(model, window)
        print(f"{label} (window={window}): {res}", flush=True)


if __name__ == "__main__":
    main()
