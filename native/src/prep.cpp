// Native prep kernels: the partitioner's per-element hot loops.
//
// The reference left these loops in pure Python with explicit
// `TODO: Perform the element loop in Cython` markers (reference:
// src/solver/partition_mesh.py:244,271,280,1170).  Here they are native:
//
//   * pcgn_csr_take       — ragged gather flat[offset[e]:offset[e+1]] for a
//                           list of elements (config_ElemVectors gather,
//                           partition_mesh.py:245-255),
//   * pcgn_unique_renumber— sorted-unique of global ids + local renumbering
//                           (the np.unique + getIndices pattern,
//                           partition_mesh.py:272-286),
//   * pcgn_sort_i32       — index argsort used to build the pre-sorted
//                           scatter maps for segment_sum.

#include <algorithm>
#include <cstdint>
#include <numeric>
#include <vector>

using i64 = int64_t;
using i32 = int32_t;

extern "C" {

// out must hold sum(offset[e+1]-offset[e] for e in elems) entries.
// Returns the number of values written.
i64 pcgn_csr_take(const i64* flat, const i64* offset, const i64* elems,
                  i64 n_elems, i64* out) {
  i64 k = 0;
  for (i64 i = 0; i < n_elems; ++i) {
    const i64 e = elems[i];
    for (i64 j = offset[e]; j < offset[e + 1]; ++j) out[k++] = flat[j];
  }
  return k;
}

// Sorted unique of ids[0..n) into uniq (capacity n) and, when loc is
// non-null, the local index of every input id into loc (int32).
// Returns the unique count.
i64 pcgn_unique_renumber(const i64* ids, i64 n, i64* uniq, i32* loc) {
  if (n == 0) return 0;
  std::vector<i64> sorted(ids, ids + n);
  std::sort(sorted.begin(), sorted.end());
  i64 nu = 0;
  i64 prev = sorted[0] - 1;
  for (i64 i = 0; i < n; ++i) {
    if (sorted[i] != prev) { prev = sorted[i]; uniq[nu++] = prev; }
  }
  if (loc) {
    for (i64 i = 0; i < n; ++i) {
      const i64* p = std::lower_bound(uniq, uniq + nu, ids[i]);
      loc[i] = (i32)(p - uniq);
    }
  }
  return nu;
}

// Stable argsort of int32 keys; perm must hold n entries, sorted_keys n.
void pcgn_sort_i32(const i32* keys, i64 n, i32* perm, i32* sorted_keys) {
  std::vector<i32> idx(n);
  std::iota(idx.begin(), idx.end(), 0);
  std::stable_sort(idx.begin(), idx.end(),
                   [&](i32 a, i32 b) { return keys[a] < keys[b]; });
  for (i64 i = 0; i < n; ++i) {
    perm[i] = idx[i];
    sorted_keys[i] = keys[idx[i]];
  }
}

}  // extern "C"
