// Native graph partitioner for pcg_mpi_solver_tpu.
//
// TPU-native replacement for the reference's METIS dual-graph partition call
// (reference: src/solver/run_metis.py:84-88, `metis.part_mesh_dual`).  The
// reference links the C METIS library through mgmetis; this framework ships
// its own native partitioner so the offline prep stage needs no external
// native dependency:
//
//   * dual-graph construction from the element->node CSR (elements adjacent
//     iff they share >= ncommon nodes),
//   * multilevel recursive-bisection k-way partitioning:
//       coarsen by heavy-edge matching -> BFS region-growing bisection of the
//       coarsest graph -> uncoarsen with Fiduccia–Mattheyses boundary
//       refinement at every level.
//
// Exposed as a tiny C ABI consumed via ctypes (no pybind11 dependency).

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <numeric>
#include <queue>
#include <random>
#include <vector>

namespace {

using i64 = int64_t;
using i32 = int32_t;

struct Graph {
  i64 n = 0;
  std::vector<i64> xadj;    // n+1
  std::vector<i64> adjncy;  // nnz
  std::vector<i64> adjwgt;  // nnz (edge weights)
  std::vector<i64> vwgt;    // n   (vertex weights)
  i64 total_vwgt = 0;
};

// ---------------------------------------------------------------------------
// Coarsening: heavy-edge matching
// ---------------------------------------------------------------------------

Graph coarsen(const Graph& g, std::vector<i64>& cmap, std::mt19937_64& rng) {
  const i64 n = g.n;
  cmap.assign(n, -1);
  std::vector<i64> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::shuffle(order.begin(), order.end(), rng);

  i64 nc = 0;
  // Heavy-edge matching: visit vertices in random order, match each unmatched
  // vertex with its unmatched neighbour of maximum edge weight.
  for (i64 oi = 0; oi < n; ++oi) {
    const i64 v = order[oi];
    if (cmap[v] >= 0) continue;
    i64 best = -1, bestw = -1;
    for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
      const i64 u = g.adjncy[e];
      if (u == v || cmap[u] >= 0) continue;
      if (g.adjwgt[e] > bestw) { bestw = g.adjwgt[e]; best = u; }
    }
    cmap[v] = nc;
    if (best >= 0) cmap[best] = nc;
    ++nc;
  }

  Graph cg;
  cg.n = nc;
  cg.vwgt.assign(nc, 0);
  for (i64 v = 0; v < n; ++v) cg.vwgt[cmap[v]] += g.vwgt[v];
  cg.total_vwgt = g.total_vwgt;

  // Build coarse adjacency by merging fine edges; dedupe with a stamp array.
  std::vector<i64> stamp(nc, -1), slot(nc, 0);
  std::vector<std::pair<i64, i64>> buf;  // (coarse neighbour, weight) scratch
  std::vector<std::vector<i64>> members(nc);
  for (i64 v = 0; v < n; ++v) members[cmap[v]].push_back(v);

  std::vector<i64> cxadj(nc + 1, 0);
  std::vector<i64> cadj, cwgt;
  cadj.reserve(g.adjncy.size());
  cwgt.reserve(g.adjncy.size());
  for (i64 c = 0; c < nc; ++c) {
    buf.clear();
    for (i64 v : members[c]) {
      for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
        const i64 cu = cmap[g.adjncy[e]];
        if (cu == c) continue;
        if (stamp[cu] != c) {
          stamp[cu] = c;
          slot[cu] = (i64)buf.size();
          buf.emplace_back(cu, g.adjwgt[e]);
        } else {
          buf[slot[cu]].second += g.adjwgt[e];
        }
      }
    }
    for (auto& [cu, w] : buf) { cadj.push_back(cu); cwgt.push_back(w); }
    cxadj[c + 1] = (i64)cadj.size();
  }
  cg.xadj = std::move(cxadj);
  cg.adjncy = std::move(cadj);
  cg.adjwgt = std::move(cwgt);
  return cg;
}

// ---------------------------------------------------------------------------
// Initial bisection: BFS region growing from a pseudo-peripheral vertex
// ---------------------------------------------------------------------------

i64 pseudo_peripheral(const Graph& g, i64 start) {
  std::vector<i32> dist(g.n, -1);
  i64 far = start;
  for (int it = 0; it < 3; ++it) {
    std::fill(dist.begin(), dist.end(), -1);
    std::queue<i64> q;
    q.push(far);
    dist[far] = 0;
    i64 last = far;
    while (!q.empty()) {
      const i64 v = q.front(); q.pop();
      last = v;
      for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
        const i64 u = g.adjncy[e];
        if (dist[u] < 0) { dist[u] = dist[v] + 1; q.push(u); }
      }
    }
    if (last == far) break;
    far = last;
  }
  return far;
}

// Grow side 0 by best-connected frontier expansion until it holds
// ~target_wgt; everything else is side 1.
void grow_bisection(const Graph& g, i64 target_wgt, std::vector<i32>& side) {
  side.assign(g.n, 1);
  if (g.n == 0) return;
  const i64 seed = pseudo_peripheral(g, 0);
  // Max-priority by connection weight to the growing region.
  std::priority_queue<std::pair<i64, i64>> pq;  // (gain, vertex)
  std::vector<i64> conn(g.n, 0);
  std::vector<char> in(g.n, 0);
  pq.emplace(0, seed);
  i64 w0 = 0;
  while (!pq.empty() && w0 < target_wgt) {
    const auto [gain, v] = pq.top(); pq.pop();
    if (in[v] || gain < conn[v]) continue;  // stale entry
    in[v] = 1;
    side[v] = 0;
    w0 += g.vwgt[v];
    for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
      const i64 u = g.adjncy[e];
      if (in[u]) continue;
      conn[u] += g.adjwgt[e];
      pq.emplace(conn[u], u);
    }
  }
  // Disconnected remainder: if we ran out of frontier early, sweep linearly.
  if (w0 < target_wgt) {
    for (i64 v = 0; v < g.n && w0 < target_wgt; ++v) {
      if (!in[v]) { in[v] = 1; side[v] = 0; w0 += g.vwgt[v]; }
    }
  }
}

// ---------------------------------------------------------------------------
// FM boundary refinement (2-way)
// ---------------------------------------------------------------------------

void fm_refine(const Graph& g, std::vector<i32>& side, i64 target0,
               double eps, int max_passes) {
  const i64 n = g.n;
  i64 w[2] = {0, 0};
  for (i64 v = 0; v < n; ++v) w[side[v]] += g.vwgt[v];
  const i64 total = w[0] + w[1];
  const i64 lo0 = (i64)((1.0 - eps) * (double)target0);
  const i64 hi0 = (i64)((1.0 + eps) * (double)target0);

  std::vector<i64> gain(n);
  std::vector<char> locked(n);

  auto compute_gain = [&](i64 v) {
    i64 in = 0, ex = 0;
    for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
      if (side[g.adjncy[e]] == side[v]) in += g.adjwgt[e];
      else ex += g.adjwgt[e];
    }
    return ex - in;
  };

  for (int pass = 0; pass < max_passes; ++pass) {
    std::fill(locked.begin(), locked.end(), 0);
    // Initialize every gain (incremental deltas during the pass assume it),
    // seed the queue with boundary vertices only.
    std::priority_queue<std::pair<i64, i64>> pq;
    for (i64 v = 0; v < n; ++v) {
      gain[v] = compute_gain(v);
      for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
        if (side[g.adjncy[e]] != side[v]) { pq.emplace(gain[v], v); break; }
      }
    }

    std::vector<i64> moves;
    i64 cum = 0, best_cum = 0;
    i64 best_prefix = 0;
    i64 moves_limit = std::max<i64>(64, n / 4);
    while (!pq.empty() && (i64)moves.size() < moves_limit) {
      const auto [gv, v] = pq.top(); pq.pop();
      if (locked[v] || gv != gain[v]) continue;
      // Balance feasibility of moving v to the other side.
      const i32 s = side[v];
      i64 nw0 = w[0] + (s == 1 ? g.vwgt[v] : -g.vwgt[v]);
      if (nw0 < lo0 || nw0 > hi0) {
        // Allow the move only if it strictly improves balance.
        if (std::llabs(nw0 - target0) >= std::llabs(w[0] - target0)) continue;
      }
      locked[v] = 1;
      side[v] = 1 - s;
      w[0] = nw0;
      w[1] = total - nw0;
      moves.push_back(v);
      cum += gv;
      if (cum > best_cum) { best_cum = cum; best_prefix = (i64)moves.size(); }
      // Incremental FM gain delta: v moved from side s to 1-s, so an edge
      // (v,u) flips between internal and external for u.
      for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
        const i64 u = g.adjncy[e];
        if (locked[u]) continue;
        gain[u] += (side[u] == s ? 2 : -2) * g.adjwgt[e];
        pq.emplace(gain[u], u);
      }
    }
    // Roll back the suffix after the best prefix.
    for (i64 i = (i64)moves.size() - 1; i >= best_prefix; --i) {
      const i64 v = moves[i];
      const i32 s = side[v];
      side[v] = 1 - s;
      w[side[v]] += g.vwgt[v];
      w[s] -= g.vwgt[v];
    }
    if (best_cum <= 0) break;
  }
}

// ---------------------------------------------------------------------------
// Multilevel bisection + recursion
// ---------------------------------------------------------------------------

void multilevel_bisect(const Graph& g, i64 target0, std::vector<i32>& side,
                       std::mt19937_64& rng) {
  constexpr i64 kCoarsestN = 128;
  if (g.n <= kCoarsestN) {
    grow_bisection(g, target0, side);
    fm_refine(g, side, target0, 0.02, 8);
    return;
  }
  std::vector<i64> cmap;
  Graph cg = coarsen(g, cmap, rng);
  if (cg.n >= g.n * 95 / 100) {
    // Matching stalled (e.g. star graphs): stop coarsening here.
    grow_bisection(g, target0, side);
    fm_refine(g, side, target0, 0.02, 8);
    return;
  }
  std::vector<i32> cside;
  multilevel_bisect(cg, target0, cside, rng);
  side.resize(g.n);
  for (i64 v = 0; v < g.n; ++v) side[v] = cside[cmap[v]];
  fm_refine(g, side, target0, 0.02, 4);
}

// Extract the subgraph induced by vertices with mask[v]==keep.
Graph subgraph(const Graph& g, const std::vector<i32>& side, i32 keep,
               std::vector<i64>& orig_ids) {
  Graph s;
  std::vector<i64> newid(g.n, -1);
  orig_ids.clear();
  for (i64 v = 0; v < g.n; ++v) {
    if (side[v] == keep) {
      newid[v] = (i64)orig_ids.size();
      orig_ids.push_back(v);
    }
  }
  s.n = (i64)orig_ids.size();
  s.xadj.assign(s.n + 1, 0);
  s.vwgt.resize(s.n);
  for (i64 i = 0; i < s.n; ++i) {
    const i64 v = orig_ids[i];
    s.vwgt[i] = g.vwgt[v];
    s.total_vwgt += g.vwgt[v];
    for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e)
      if (newid[g.adjncy[e]] >= 0) ++s.xadj[i + 1];
  }
  for (i64 i = 0; i < s.n; ++i) s.xadj[i + 1] += s.xadj[i];
  s.adjncy.resize(s.xadj[s.n]);
  s.adjwgt.resize(s.xadj[s.n]);
  std::vector<i64> pos(s.xadj.begin(), s.xadj.end() - 1);
  for (i64 i = 0; i < s.n; ++i) {
    const i64 v = orig_ids[i];
    for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
      const i64 u = newid[g.adjncy[e]];
      if (u >= 0) { s.adjncy[pos[i]] = u; s.adjwgt[pos[i]] = g.adjwgt[e]; ++pos[i]; }
    }
  }
  return s;
}

void recursive_partition(const Graph& g, int n_parts, int part0,
                         const std::vector<i64>& orig_ids, i32* part_out,
                         std::mt19937_64& rng) {
  if (n_parts == 1 || g.n == 0) {
    for (i64 v = 0; v < g.n; ++v) part_out[orig_ids[v]] = (i32)part0;
    return;
  }
  const int n_left = n_parts / 2;
  const i64 target0 = (i64)((double)g.total_vwgt * (double)n_left / (double)n_parts);
  std::vector<i32> side;
  multilevel_bisect(g, target0, side, rng);

  std::vector<i64> ids0, ids1;
  Graph g0 = subgraph(g, side, 0, ids0);
  Graph g1 = subgraph(g, side, 1, ids1);
  for (auto& id : ids0) id = orig_ids[id];
  for (auto& id : ids1) id = orig_ids[id];
  recursive_partition(g0, n_left, part0, ids0, part_out, rng);
  recursive_partition(g1, n_parts - n_left, part0 + n_left, ids1, part_out, rng);
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// Partition a general graph (CSR) into n_parts; part_out must hold n int32.
// vwgt may be null (unit weights).  Returns 0 on success.
int pcgn_part_graph(i64 n, const i64* xadj, const i64* adjncy,
                    const i64* adjwgt, const i64* vwgt, int n_parts,
                    uint64_t seed, i32* part_out) {
  if (n < 0 || n_parts < 1) return 1;
  if (n_parts == 1 || n == 0) {
    for (i64 v = 0; v < n; ++v) part_out[v] = 0;
    return 0;
  }
  Graph g;
  g.n = n;
  g.xadj.assign(xadj, xadj + n + 1);
  g.adjncy.assign(adjncy, adjncy + xadj[n]);
  if (adjwgt) g.adjwgt.assign(adjwgt, adjwgt + xadj[n]);
  else g.adjwgt.assign(xadj[n], 1);
  if (vwgt) g.vwgt.assign(vwgt, vwgt + n);
  else g.vwgt.assign(n, 1);
  g.total_vwgt = std::accumulate(g.vwgt.begin(), g.vwgt.end(), (i64)0);

  std::vector<i64> ids(n);
  std::iota(ids.begin(), ids.end(), 0);
  std::mt19937_64 rng(seed);
  recursive_partition(g, n_parts, 0, ids, part_out, rng);
  return 0;
}

// Build the dual graph of a mesh (elements adjacent iff they share
// >= ncommon nodes) and partition it.  eptr/eind is the element->node CSR
// (eptr has n_elem+1 entries).  part_out must hold n_elem int32.
// Mirrors the call shape of METIS part_mesh_dual (run_metis.py:88).
int pcgn_part_mesh_dual(i64 n_elem, i64 n_node, const i64* eptr,
                        const i64* eind, int ncommon, int n_parts,
                        uint64_t seed, i32* part_out) {
  if (n_elem < 0 || n_parts < 1 || ncommon < 1) return 1;
  if (n_parts == 1 || n_elem == 0) {
    for (i64 e = 0; e < n_elem; ++e) part_out[e] = 0;
    return 0;
  }
  // node -> element inverse CSR
  std::vector<i64> ncnt(n_node + 1, 0);
  for (i64 i = 0; i < eptr[n_elem]; ++i) ++ncnt[eind[i] + 1];
  for (i64 i = 0; i < n_node; ++i) ncnt[i + 1] += ncnt[i];
  std::vector<i64> nelems(eptr[n_elem]);
  {
    std::vector<i64> pos(ncnt.begin(), ncnt.end() - 1);
    for (i64 e = 0; e < n_elem; ++e)
      for (i64 i = eptr[e]; i < eptr[e + 1]; ++i) nelems[pos[eind[i]]++] = e;
  }

  // Dual adjacency with shared-node counts (edge weight = #shared nodes).
  std::vector<i64> xadj(n_elem + 1, 0), adjncy, adjwgt;
  adjncy.reserve(n_elem * 6);
  adjwgt.reserve(n_elem * 6);
  std::vector<i64> stamp(n_elem, -1), cnt(n_elem, 0), touched;
  for (i64 e = 0; e < n_elem; ++e) {
    touched.clear();
    for (i64 i = eptr[e]; i < eptr[e + 1]; ++i) {
      const i64 nd = eind[i];
      for (i64 j = ncnt[nd]; j < ncnt[nd + 1]; ++j) {
        const i64 u = nelems[j];
        if (u == e) continue;
        if (stamp[u] != e) { stamp[u] = e; cnt[u] = 0; touched.push_back(u); }
        ++cnt[u];
      }
    }
    for (i64 u : touched) {
      if (cnt[u] >= ncommon) { adjncy.push_back(u); adjwgt.push_back(cnt[u]); }
    }
    xadj[e + 1] = (i64)adjncy.size();
  }

  return pcgn_part_graph(n_elem, xadj.data(), adjncy.data(), adjwgt.data(),
                         nullptr, n_parts, seed, part_out);
}

// Edge cut of a partition (diagnostics / tests).
i64 pcgn_edge_cut(i64 n, const i64* xadj, const i64* adjncy,
                  const i64* adjwgt, const i32* part) {
  i64 cut = 0;
  for (i64 v = 0; v < n; ++v)
    for (i64 e = xadj[v]; e < xadj[v + 1]; ++e)
      if (part[v] != part[adjncy[e]]) cut += adjwgt ? adjwgt[e] : 1;
  return cut / 2;
}

}  // extern "C"
